"""Metric history (obs/tsdb.py) + alert engine (obs/alerts.py).

The store and query primitives are tested with explicit timestamps; the
alert state machine with a synthetic :class:`SeriesStore` and a fake
clock, so every pending -> firing -> resolved transition is
deterministic. The gate-off path is hash-pinned through the goldens
mechanism (the serving path must stay byte-identical with SDTPU_TSDB /
SDTPU_ALERTS unset).
"""

import json
import urllib.request

import pytest

from stable_diffusion_webui_distributed_tpu.obs import alerts as obs_alerts
from stable_diffusion_webui_distributed_tpu.obs import flightrec
from stable_diffusion_webui_distributed_tpu.obs import journal as obs_journal
from stable_diffusion_webui_distributed_tpu.obs import (
    prometheus as obs_prom,
)
from stable_diffusion_webui_distributed_tpu.obs import tsdb as obs_tsdb
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.serving.bucketer import (
    ShapeBucketer,
)
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (
    ServingDispatcher,
)
from test_goldens import _check
from test_pipeline import init_params


@pytest.fixture()
def tsdb_on(monkeypatch):
    monkeypatch.setenv("SDTPU_TSDB", "1")
    obs_tsdb.reset()
    yield obs_tsdb.STORE
    obs_tsdb.reset()


@pytest.fixture()
def alerts_on(monkeypatch):
    monkeypatch.setenv("SDTPU_TSDB", "1")
    monkeypatch.setenv("SDTPU_ALERTS", "1")
    obs_tsdb.reset()
    obs_alerts.reset()
    yield obs_alerts.ENGINE
    obs_alerts.reset()
    obs_tsdb.reset()


# -- derived-series math -----------------------------------------------------

class TestQuantileFromCounts:
    def test_interpolates_inside_the_bucket(self):
        # 10 samples uniformly in the (1.0, 2.0] bucket: rank
        # interpolation spreads them across the bucket instead of
        # reporting the 2.0 upper bound for every quantile
        bounds = (1.0, 2.0, 4.0)
        counts = [0, 10, 0, 0]  # incl. +Inf overflow slot
        q50 = obs_tsdb.quantile_from_counts(bounds, counts, 10, 0.5)
        q95 = obs_tsdb.quantile_from_counts(bounds, counts, 10, 0.95)
        assert 1.0 < q50 < q95 <= 2.0
        assert q50 == pytest.approx(1.5)

    def test_overflow_bucket_clamps_to_top_bound(self):
        bounds = (1.0, 2.0)
        counts = [0, 0, 5]  # everything in +Inf
        assert obs_tsdb.quantile_from_counts(bounds, counts, 5, 0.95) == 2.0

    def test_empty_histogram_is_zero(self):
        assert obs_tsdb.quantile_from_counts((1.0,), [0, 0], 0, 0.95) == 0.0


# -- the store ---------------------------------------------------------------

class TestSeriesStore:
    def _store(self, points=32):
        return obs_tsdb.SeriesStore(points=points)

    def test_ring_is_bounded_and_ordered(self):
        s = self._store(points=8)
        for i in range(20):
            s.record("x", float(i), t=float(i))
        w = s.window("x", 0)  # <=0 window: the whole ring
        assert len(w) == 8
        assert [v for _t, v in w] == [float(i) for i in range(12, 20)]
        assert s.latest("x") == (19.0, 19.0)

    def test_non_numeric_samples_are_dropped(self):
        s = self._store()
        s.record("x", "not-a-number", t=1.0)
        s.record("x", None, t=2.0)
        assert s.names() == []
        assert s.stats()["samples_total"] == 0

    def test_window_filters_by_time(self):
        s = self._store()
        for t in (1.0, 5.0, 9.0):
            s.record("x", t, t=t)
        assert [t for t, _v in s.window("x", 5.0, now=10.0)] == [5.0, 9.0]

    def test_rate_and_increase(self):
        s = self._store()
        s.record("c", 10.0, t=0.0)
        s.record("c", 30.0, t=10.0)
        assert s.rate("c", 60.0, now=10.0) == pytest.approx(2.0)
        assert s.increase("c", 60.0, now=10.0) == pytest.approx(20.0)
        # under 2 samples in the window -> None, not 0
        assert s.rate("c", 5.0, now=10.0) is None
        assert s.increase("missing", 60.0) is None

    def test_avg_and_quantile_over_time(self):
        s = self._store()
        for i, v in enumerate([1.0, 2.0, 3.0, 10.0]):
            s.record("x", v, t=float(i))
        assert s.avg_over_time("x", 100.0, now=4.0) == pytest.approx(4.0)
        assert s.quantile_over_time("x", 0.5, 100.0, now=4.0) \
            == pytest.approx(2.5)
        assert s.quantile_over_time("x", 1.0, 100.0, now=4.0) == 10.0
        assert s.quantile_over_time("x", 0.5, 100.0, now=1e9) is None

    def test_series_namespace_is_capped(self):
        s = self._store()
        for i in range(obs_tsdb._MAX_SERIES + 5):
            s.record(f"adversarial.{i}", 1.0, t=1.0)
        st = s.stats()
        assert st["series"] == obs_tsdb._MAX_SERIES
        assert st["dropped_series"] == 5

    def test_snapshot_schema_and_trim(self):
        s = self._store()
        for i in range(6):
            s.record("x", float(i), t=float(i))
        snap = s.snapshot(max_points=3)
        assert set(snap) == {"x"}
        assert set(snap["x"]) == {"count", "latest", "samples"}
        assert snap["x"]["count"] == 3
        assert snap["x"]["samples"] == [[3.0, 3.0], [4.0, 4.0], [5.0, 5.0]]
        assert snap["x"]["latest"] == [5.0, 5.0]


class TestSamplingAndGate:
    def test_tick_is_a_noop_with_the_gate_off(self, monkeypatch):
        monkeypatch.delenv("SDTPU_TSDB", raising=False)
        obs_tsdb.reset()
        assert obs_tsdb.enabled() is False
        assert obs_tsdb.tick() == 0
        assert obs_tsdb.STORE.names() == []
        assert obs_tsdb.start_daemon() is False
        assert obs_tsdb.flight_window() is None

    def test_sample_once_lands_counter_series(self, tsdb_on):
        obs_prom.observe_hist("queue_wait", 0.2)
        obs_prom.observe_hist("e2e", 1.0)
        landed = obs_tsdb.tick()
        assert landed > 0
        names = set(obs_tsdb.STORE.names())
        assert {"queue_wait_p95_s", "e2e_p95_s", "worker_failures_total",
                "watchdog_stalls_total"} <= names

    def test_daemon_starts_and_stops(self, tsdb_on, monkeypatch):
        monkeypatch.setenv("SDTPU_TSDB_INTERVAL_S", "0.01")
        assert obs_tsdb.start_daemon() is True
        assert obs_tsdb.start_daemon() is True  # idempotent
        assert obs_tsdb.summary()["daemon"] is True
        obs_tsdb.stop_daemon()
        assert obs_tsdb.summary()["daemon"] is False

    def test_points_knob_resizes_on_reset(self, tsdb_on, monkeypatch):
        monkeypatch.setenv("SDTPU_TSDB_POINTS", "16")
        obs_tsdb.reset()
        try:
            assert obs_tsdb.STORE.points == 16
        finally:
            monkeypatch.delenv("SDTPU_TSDB_POINTS")
            obs_tsdb.reset()

    def test_summary_schema(self, tsdb_on):
        obs_tsdb.tick()
        doc = obs_tsdb.summary()
        assert set(doc) == {"enabled", "interval_s", "points", "daemon",
                            "series_count", "samples_total",
                            "dropped_series", "series"}
        assert doc["enabled"] is True
        assert doc["series_count"] == len(doc["series"])

    def test_flight_window_is_bounded_and_filtered(self, tsdb_on):
        for i in range(100):
            obs_tsdb.STORE.record("worker_failures_total", float(i),
                                  t=float(i))
            obs_tsdb.STORE.record("slo_burn.t.interactive", 1.0, t=float(i))
            obs_tsdb.STORE.record("requests_total", float(i), t=float(i))
        win = obs_tsdb.flight_window()
        assert set(win) == {"interval_s", "series"}
        assert set(win["series"]) == {"worker_failures_total",
                                      "slo_burn.t.interactive"}
        for doc in win["series"].values():
            assert doc["count"] <= obs_tsdb._FLIGHT_POINTS


# -- the alert engine --------------------------------------------------------

def _engine_with_store():
    """A synthetic store + fake-clock engine: tests advance ``clock[0]``
    and record samples with explicit timestamps."""
    store = obs_tsdb.SeriesStore(points=128)
    clock = [0.0]
    engine = obs_alerts.AlertEngine(store=store,
                                    clock=lambda: clock[0])
    return store, clock, engine


class TestAlertEngine:
    def test_increase_rule_fires_and_resolves(self, alerts_on):
        store, clock, eng = _engine_with_store()
        store.record("watchdog_stalls_total", 0.0, t=0.0)
        clock[0] = 1.0
        store.record("watchdog_stalls_total", 0.0, t=1.0)
        assert eng.evaluate() == []  # flat counter: no transition
        clock[0] = 2.0
        store.record("watchdog_stalls_total", 1.0, t=2.0)
        (t,) = eng.evaluate()
        assert (t["rule"], t["from"], t["to"]) == \
            ("watchdog_stall", "ok", "firing")
        assert eng.firing() == ["watchdog_stall"]
        # the stall ages out of the fast window -> resolved
        clock[0] = 4000.0
        store.record("watchdog_stalls_total", 1.0, t=3999.0)
        store.record("watchdog_stalls_total", 1.0, t=4000.0)
        (t,) = eng.evaluate()
        assert (t["rule"], t["from"], t["to"]) == \
            ("watchdog_stall", "firing", "ok")
        assert eng.firing() == []

    def test_burn_rule_needs_both_windows(self, alerts_on, monkeypatch):
        monkeypatch.setenv("SDTPU_ALERT_TIMESCALE", "0.01")  # 3s / 36s
        store, clock, eng = _engine_with_store()
        # long window hot, short window cooled off: min(short, long)
        # stays under threshold -> no alert (the anti-flap property)
        for t in range(0, 30):
            store.record("slo_burn.t.rt", 20.0, t=float(t))
        for t in range(30, 36):
            store.record("slo_burn.t.rt", 1.0, t=float(t))
        clock[0] = 36.0
        first = {t["rule"] for t in eng.evaluate() if t["to"] == "firing"}
        assert "slo_burn_fast" not in first  # fast window cooled off
        # both fast windows over 14.4 -> slo_burn_fast fires
        for t in range(36, 40):
            store.record("slo_burn.t.rt", 30.0, t=float(t))
        clock[0] = 40.0
        fired = {t["rule"] for t in eng.evaluate() if t["to"] == "firing"}
        assert "slo_burn_fast" in fired
        assert eng.scale_up_firing() == sorted(
            n for n in eng.firing()
            if obs_alerts.registered_rules()[n].scale_up)

    def test_anomaly_rule_warms_up_then_latches(self, alerts_on):
        store, clock, eng = _engine_with_store()
        rule = obs_alerts.registered_rules()["queue_wait_anomaly"]
        # flat baseline through warmup: never fires
        for i in range(rule.warmup + 2):
            clock[0] = float(i)
            store.record("queue_wait_p95_s", 0.05, t=float(i))
            assert eng.evaluate() == []
        # a runaway regime change (the EWMA chases, so only an
        # escalating series stays z-anomalous) must sustain for_count
        # evaluations: pending on the first hit, firing on the last
        states = []
        for i, v in enumerate([5.0, 50.0, 500.0][:rule.for_count]):
            clock[0] = 100.0 + i
            store.record("queue_wait_p95_s", v, t=100.0 + i)
            eng.evaluate()
            states.append(eng.state()["rules"]["queue_wait_anomaly"]
                          ["state"])
        assert states[:-1] == ["pending"] * (rule.for_count - 1)
        assert states[-1] == "firing"

    def test_anomaly_min_value_floor_blocks_quiet_series(self, alerts_on):
        store, clock, eng = _engine_with_store()
        # z-score explodes (0.001 -> 0.1) but stays under the 0.25s
        # absolute floor: a quiet series cannot alarm on noise
        for i in range(12):
            clock[0] = float(i)
            store.record("queue_wait_p95_s", 0.001, t=float(i))
            eng.evaluate()
        clock[0] = 50.0
        store.record("queue_wait_p95_s", 0.1, t=50.0)
        assert eng.evaluate() == []

    def test_pending_self_clears_on_a_single_spike(self, alerts_on):
        store, clock, eng = _engine_with_store()
        for i in range(12):
            clock[0] = float(i)
            store.record("queue_wait_p95_s", 0.05, t=float(i))
            eng.evaluate()
        clock[0] = 50.0
        store.record("queue_wait_p95_s", 5.0, t=50.0)
        eng.evaluate()
        assert eng.state()["rules"]["queue_wait_anomaly"]["state"] \
            == "pending"
        # back to baseline before for_count sustains -> ok, no firing
        for i in range(3):
            clock[0] = 51.0 + i
            store.record("queue_wait_p95_s", 0.05, t=51.0 + i)
            eng.evaluate()
        st = eng.state()["rules"]["queue_wait_anomaly"]
        assert st["state"] == "ok"
        assert all(e["to"] != "firing" for e in eng.history())

    def test_history_entry_shape_and_bound(self, alerts_on):
        store, clock, eng = _engine_with_store()
        store.record("watchdog_stalls_total", 0.0, t=0.0)
        store.record("watchdog_stalls_total", 1.0, t=1.0)
        clock[0] = 1.0
        eng.evaluate()
        (e,) = eng.history()
        assert set(e) == {"rule", "from", "to", "t", "value", "detail"}
        assert eng._history.maxlen == obs_alerts._HISTORY_CAP

    def test_gated_module_functions(self, monkeypatch):
        monkeypatch.delenv("SDTPU_ALERTS", raising=False)
        assert obs_alerts.evaluate() == []
        assert obs_alerts.firing() == []
        assert obs_alerts.scale_up_firing() == []
        assert obs_alerts.state_snapshot() is None

    def test_summary_schema(self, alerts_on):
        doc = obs_alerts.summary()
        assert set(doc) == {"enabled", "timescale", "registered",
                            "rules", "firing", "history"}
        assert doc["enabled"] is True
        assert set(doc["registered"]) == set(obs_alerts.registered_rules())
        for meta in doc["registered"].values():
            assert set(meta) == {"kind", "series", "description",
                                 "scale_up", "severity"}
            assert meta["severity"] in obs_alerts.SEVERITIES

    def test_reregistering_a_rule_name_raises(self):
        with pytest.raises(ValueError, match="already registered"):
            # construction is fine anywhere; double registration is not
            obs_alerts.register_rule(obs_alerts.AlertRule(
                name="watchdog_stall", kind="increase", series="x",
                description="collides"))  # sdtpu-lint: alert


class TestAlertSideEffects:
    def test_firing_journals_and_exports_metrics(self, alerts_on,
                                                 monkeypatch):
        monkeypatch.setenv("SDTPU_JOURNAL", "1")
        obs_journal.JOURNAL.clear()
        fired0 = obs_prom.ALERT_COUNTER.value(rule="watchdog_stall",
                                              state="firing")
        resolved0 = obs_prom.ALERT_COUNTER.value(rule="watchdog_stall",
                                                 state="resolved")
        store, clock, eng = _engine_with_store()
        store.record("watchdog_stalls_total", 0.0, t=0.0)
        store.record("watchdog_stalls_total", 1.0, t=1.0)
        clock[0] = 1.0
        eng.evaluate()
        clock[0] = 4000.0
        store.record("watchdog_stalls_total", 1.0, t=3999.0)
        store.record("watchdog_stalls_total", 1.0, t=4000.0)
        eng.evaluate()
        names = [e["event"] for e in
                 obs_journal.JOURNAL.snapshot()["events"]]
        assert names == ["alert_firing", "alert_resolved"]
        # closed vocabulary: both names are registered journal events
        assert {"alert_firing", "alert_resolved"} <= obs_journal.EVENTS
        assert obs_prom.alert_states().get("watchdog_stall") == 0.0
        assert obs_prom.ALERT_COUNTER.value(
            rule="watchdog_stall", state="firing") == fired0 + 1.0
        assert obs_prom.ALERT_COUNTER.value(
            rule="watchdog_stall", state="resolved") == resolved0 + 1.0
        obs_journal.JOURNAL.clear()

    def test_firing_lands_a_flightrec_entry(self, alerts_on):
        flightrec.RECORDER.clear()
        store, clock, eng = _engine_with_store()
        store.record("watchdog_stalls_total", 0.0, t=0.0)
        store.record("watchdog_stalls_total", 1.0, t=1.0)
        clock[0] = 1.0
        eng.evaluate()
        entries = [e for e in flightrec.RECORDER.dump()["entries"]
                   if e["reason"] == "alert_firing"]
        assert len(entries) == 1
        assert entries[0]["request_id"] == "alert-watchdog_stall"
        # enrichment: the entry carries the alert state + TSDB window
        assert entries[0]["alerts"] is not None
        assert entries[0]["tsdb"] is not None
        flightrec.RECORDER.clear()

    def test_flightrec_enrichment_is_none_with_gates_off(self,
                                                         monkeypatch):
        monkeypatch.delenv("SDTPU_TSDB", raising=False)
        monkeypatch.delenv("SDTPU_ALERTS", raising=False)
        flightrec.RECORDER.clear()
        entry = flightrec.RECORDER.record("rid-x", "failure", "boom",
                                          events=[])
        assert entry["alerts"] is None
        assert entry["tsdb"] is None
        flightrec.RECORDER.clear()


class TestAutoscaleAlertSignal:
    def test_firing_alert_triggers_scale_up_with_audit(self):
        from stable_diffusion_webui_distributed_tpu.fleet import slices

        reg = slices.SliceRegistry()
        reg.register(slices.SliceInfo(name="s0", group="g", replicas=1,
                                      min_replicas=1, max_replicas=4))
        eng = slices.AutoscaleEngine(
            reg, quantile_source=lambda: 0.0,  # p95 alone says "down"
            up_p95_s=5.0, down_p95_s=0.5, cooldown_s=0.0,
            alert_source=lambda: ["queue_wait_anomaly"])
        try:
            (d,) = eng.decide()
            assert d.direction == "up"
            assert "alert queue_wait_anomaly firing" in d.reason
            assert reg.summary()["s0"]["replicas"] == 2
            audit = eng.audit()
            assert audit["firing_alerts"] == ["queue_wait_anomaly"]
            assert audit["decisions"][-1]["reason"] == d.reason
        finally:
            slices.set_autoscale(None)

    def test_default_alert_source_is_gated(self, monkeypatch):
        from stable_diffusion_webui_distributed_tpu.fleet import slices

        monkeypatch.delenv("SDTPU_ALERTS", raising=False)
        assert slices._default_alert_source() == []


# -- HTTP surfaces -----------------------------------------------------------

class TestHttpSurfaces:
    @pytest.fixture(scope="class")
    def server(self):
        from stable_diffusion_webui_distributed_tpu.runtime.config import (
            ConfigModel,
        )
        from stable_diffusion_webui_distributed_tpu.scheduler.worker \
            import StubBackend, WorkerNode
        from stable_diffusion_webui_distributed_tpu.scheduler.world \
            import World
        from stable_diffusion_webui_distributed_tpu.server.api import (
            ApiServer,
        )

        w = World(ConfigModel())
        w.add_worker(WorkerNode("m", StubBackend(), master=True,
                                avg_ipm=10.0))
        srv = ApiServer(w, state=GenerationState(),
                        host="127.0.0.1", port=0).start()
        yield srv
        srv.stop()

    def _get(self, server, route):
        url = f"http://127.0.0.1:{server.port}{route}"
        with urllib.request.urlopen(url, timeout=30) as r:
            return json.loads(r.read())

    def test_tsdb_endpoint_schema(self, server, tsdb_on):
        obs_tsdb.tick()
        doc = self._get(server, "/internal/tsdb")
        assert set(doc) == {"enabled", "interval_s", "points", "daemon",
                            "series_count", "samples_total",
                            "dropped_series", "series"}
        assert doc["enabled"] is True
        for series in doc["series"].values():
            assert set(series) == {"count", "latest", "samples"}

    def test_alerts_endpoint_schema(self, server, alerts_on):
        doc = self._get(server, "/internal/alerts")
        assert set(doc) == {"enabled", "timescale", "registered",
                            "rules", "firing", "history"}
        assert set(doc["rules"]) == set(doc["registered"])

    def test_endpoints_report_disabled_when_gated_off(self, server,
                                                      monkeypatch):
        monkeypatch.delenv("SDTPU_TSDB", raising=False)
        monkeypatch.delenv("SDTPU_ALERTS", raising=False)
        assert self._get(server, "/internal/tsdb")["enabled"] is False
        assert self._get(server, "/internal/alerts")["enabled"] is False


# -- device-memory telemetry -------------------------------------------------

class TestDeviceMemory:
    def test_cpu_reports_none_never_fabricates(self, tsdb_on):
        # CPU memory_stats() is empty/absent: the sampler must report
        # None and record no hbm_* series (pinned on the CPU test rig)
        stats = obs_tsdb.device_memory_stats()
        if stats is None:
            assert obs_tsdb.dispatch_memory_sample() is None
            assert not any(n.startswith("hbm_")
                           for n in obs_tsdb.STORE.names())
        else:  # accelerator rig: the stats must be real ints
            assert all(isinstance(v, int) for v in stats.values())

    def test_dispatch_memory_sample_gated_off(self, monkeypatch):
        monkeypatch.delenv("SDTPU_TSDB", raising=False)
        obs_tsdb.reset()
        obs_tsdb.dispatch_memory_sample()
        assert obs_tsdb.STORE.names() == []


# -- the gate-off serving path is byte-identical -----------------------------

class TestDefaultPathPinned:
    def test_tsdb_off_serving_path_hash_pinned(self, monkeypatch):
        monkeypatch.delenv("SDTPU_TSDB", raising=False)
        monkeypatch.delenv("SDTPU_ALERTS", raising=False)
        obs_tsdb.reset()
        obs_alerts.reset()
        engine = Engine(TINY, init_params(TINY), chunk_size=4,
                        state=GenerationState())
        disp = ServingDispatcher(
            engine, bucketer=ShapeBucketer(shapes=[(32, 32)], batches=[1]),
            window=0.0)
        r = disp.submit(GenerationPayload(
            prompt="a golden scenario cow", width=32, height=32,
            steps=4, seed=4321, sampler_name="Euler a"))
        _check("serving/tsdb-off-default", r)
        # and nothing leaked into the store or engine along the way
        assert obs_tsdb.STORE.names() == []
        assert obs_alerts.ENGINE.history() == []

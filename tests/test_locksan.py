"""Tests for the runtime lockset sanitizer (runtime/locksan.py).

Three layers:

- default-off guarantees: importing the module patches nothing, and a
  deterministic lock-using workload produces byte-identical results with
  the sanitizer on and off (the wrapper observes, never alters);
- wrapper mechanics: creation-site naming, nested-acquisition edge
  recording, Condition.wait() stack hygiene;
- divergence detection: synthetic observed/static graph pairs, including
  the transitive-path case and the anonymous-lock exemption.

The full-package integration (observed edges from a real test run diffed
against the static graph at session teardown) lives in tests/conftest.py
under ``SDTPU_LOCKSAN=1``.
"""

import hashlib
import os
import threading
import time

import pytest

from stable_diffusion_webui_distributed_tpu.runtime import locksan

LOCKSAN_ON = os.environ.get("SDTPU_LOCKSAN") == "1"


@pytest.fixture
def sanitized():
    """Install the sanitizer for one test, restoring prior state after."""
    was = locksan.installed()
    locksan.install()
    locksan.reset()
    yield
    locksan.reset()
    if not was:
        locksan.uninstall()


def _workload():
    """Deterministic lock-using computation; returns a digest."""
    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv_lock = threading.RLock()
            self.values = []

        def record(self, v):
            with self._lock:
                with self._cv_lock:
                    self.values.append(v * 3 + 1)

    c = Counter()
    threads = [threading.Thread(target=c.record, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    payload = ",".join(str(v) for v in sorted(c.values)).encode()
    return hashlib.sha256(payload).hexdigest()


class TestDefaultOff:
    @pytest.mark.skipif(LOCKSAN_ON, reason="conftest installed the sanitizer")
    def test_import_patches_nothing(self):
        assert not locksan.installed()
        assert threading.Lock is locksan._real_lock
        assert threading.RLock is locksan._real_rlock

    def test_workload_is_byte_identical_on_and_off(self, sanitized):
        with_san = _workload()
        was = locksan.installed()
        locksan.uninstall()
        try:
            without = _workload()
        finally:
            if was:
                locksan.install()
        assert with_san == without

    def test_uninstall_restores_real_factories(self):
        was = locksan.installed()
        locksan.install()
        locksan.uninstall()
        assert threading.Lock is locksan._real_lock
        assert threading.RLock is locksan._real_rlock
        if was:
            locksan.install()


class TestWrapperMechanics:
    def test_creation_site_naming(self, sanitized):
        class WorkerNode:
            def __init__(self):
                self._lock = threading.Lock()

        node = WorkerNode()
        assert isinstance(node._lock, locksan._SanLock)
        assert node._lock._san_name == "WorkerNode._lock"

    def test_module_level_lock_is_anonymous(self, sanitized):
        lock = threading.Lock()
        assert isinstance(lock, locksan._SanLock)
        assert lock._san_name is None

    def test_nested_acquisition_records_edge(self, sanitized):
        class Pair:
            def __init__(self):
                self.outer = threading.Lock()
                self.inner = threading.Lock()

        p = Pair()
        with p.outer:
            with p.inner:
                pass
        assert ("Pair.outer", "Pair.inner") in locksan.observed_edges()
        assert ("Pair.inner", "Pair.outer") not in locksan.observed_edges()

    def test_anonymous_locks_record_no_edges(self, sanitized):
        a, b = threading.Lock(), threading.Lock()
        with a:
            with b:
                pass
        assert not locksan.observed_edges()

    def test_condition_wait_pops_the_held_stack(self, sanitized):
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.cv = threading.Condition(self._lock)

        box = Box()
        hits = []

        def waiter():
            with box.cv:
                box.cv.wait()
                hits.append(len(locksan._held_stack()))

        t = threading.Thread(target=waiter)
        t.start()
        # keep notifying until the waiter wakes: wait() must have
        # RELEASED the wrapped lock or these acquires would deadlock
        import time
        deadline = time.monotonic() + 5
        while not hits and time.monotonic() < deadline:
            with box.cv:
                box.cv.notify()
        t.join(timeout=5)
        assert hits == [1]  # cv reacquired -> exactly the cv lock held


class TestOrderingChecks:
    """The SDTPU_LOCKSAN_ORDER session layer: Goodlock cycles over the
    union of per-thread edges, and wait-while-holding detection."""

    def _run_in_thread(self, fn):
        t = threading.Thread(target=fn)
        t.start()
        t.join(timeout=5)
        assert not t.is_alive()

    def test_opposite_orders_in_two_threads_form_a_cycle(self, sanitized):
        class Pair:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

        p = Pair()

        def forward():
            with p.a:
                with p.b:
                    pass

        def backward():
            with p.b:
                with p.a:
                    pass

        self._run_in_thread(forward)
        assert locksan.runtime_cycles() == []  # one order alone is fine
        self._run_in_thread(backward)
        cycles = locksan.runtime_cycles()
        assert cycles, "AB/BA across two threads must report a cycle"
        assert {"Pair.a", "Pair.b"} <= set(cycles[0])

    def test_edges_by_thread_keeps_threads_apart(self, sanitized):
        class Pair:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

        p = Pair()

        def forward():
            with p.a:
                with p.b:
                    pass

        self._run_in_thread(forward)
        per_thread = locksan.edges_by_thread()
        # exactly one recording thread, holding exactly the one edge
        assert [{("Pair.a", "Pair.b")}] == list(per_thread.values())

    def test_wait_while_holding_unrelated_lock_is_flagged(self, sanitized):
        class Box:
            def __init__(self):
                self.outer = threading.Lock()
                self._lock = threading.Lock()
                self.cv = threading.Condition(self._lock)

        box = Box()

        def bad_waiter():
            with box.outer:       # unrelated lock held across the wait
                with box.cv:
                    box.cv.wait(timeout=0.01)

        self._run_in_thread(bad_waiter)
        violations = locksan.wait_violations()
        assert violations, "wait under an unrelated lock must be recorded"
        held, cv_name, _thread = violations[0]
        assert "Box.outer" in held
        assert cv_name == "Box._lock"

    def test_wait_holding_only_the_cv_lock_is_clean(self, sanitized):
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.cv = threading.Condition(self._lock)

        box = Box()

        def good_waiter():
            with box.cv:
                box.cv.wait(timeout=0.01)

        self._run_in_thread(good_waiter)
        assert locksan.wait_violations() == []

    def test_thread_start_bootstrap_wait_is_exempt(self, sanitized):
        """Thread.start blocks on the child's _started event; the
        interpreter sets it before any user code runs, so starting a
        thread while holding a lock can't deadlock and must not be
        flagged. Delay the child's set() so the parent deterministically
        loses the bootstrap race and really enters the cond wait."""
        class Owner:
            def __init__(self):
                self._lock = threading.Lock()

        owner = Owner()
        child = threading.Thread(target=lambda: None, daemon=True)
        started = child._started  # sanitized Event: built post-install
        real_set = started.set

        def slow_set():
            time.sleep(0.05)
            real_set()

        started.set = slow_set
        with owner._lock:
            child.start()
        child.join(timeout=5)
        assert locksan.wait_violations() == []


class TestDivergence:
    def test_consistent_order_is_clean(self):
        static = {"A.l": {"B.l"}, "B.l": {"C.l"}}
        assert locksan.divergence({("A.l", "B.l")}, static) == []

    def test_transitive_static_path_is_clean(self):
        # observed A->C with static A->B->C: the model covers it
        static = {"A.l": {"B.l"}, "B.l": {"C.l"}}
        assert locksan.divergence({("A.l", "C.l")}, static) == []

    def test_inverted_edge_is_reported(self):
        static = {"A.l": {"B.l"}}
        assert locksan.divergence({("B.l", "A.l")}, static) == [
            ("B.l", "A.l")]

    def test_unknown_nodes_are_exempt(self):
        # an edge touching a lock the static model never saw cannot
        # diverge — the sanitizer only checks what the model claims
        static = {"A.l": {"B.l"}}
        assert locksan.divergence({("A.l", "Ghost.l")}, static) == []

    def test_static_graph_of_the_repo_is_acyclic_shaped(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        graph = locksan.static_graph(repo)
        assert isinstance(graph, dict)
        for src, dsts in graph.items():
            assert "." in src
            assert src not in dsts  # no self-loops in a clean gate

"""Core runtime tests: config persistence/migration, RNG seed contract, logging."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stable_diffusion_webui_distributed_tpu.runtime import rng
from stable_diffusion_webui_distributed_tpu.runtime.config import (
    BenchmarkPayload,
    ConfigModel,
    WorkerModel,
    load_config,
    save_config,
)
from stable_diffusion_webui_distributed_tpu.runtime.logging import (
    configure,
    get_ring_buffer,
)


class TestConfig:
    def test_defaults_match_reference_schema(self):
        cfg = ConfigModel()
        # Reference defaults: pmodels.py:42 job_timeout=3; shared.py:67-77 payload.
        assert cfg.job_timeout == 3
        bp = cfg.benchmark_payload
        assert bp.prompt.startswith("A herd of cows")
        assert (bp.width, bp.height, bp.steps, bp.batch_size) == (512, 512, 20, 1)

    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "cfg.json")
        cfg = ConfigModel(
            workers=[{"slice0": WorkerModel(address="10.0.0.2", avg_ipm=12.5)}],
            job_timeout=7,
        )
        save_config(cfg, path)
        loaded = load_config(path)
        assert loaded.job_timeout == 7
        assert loaded.workers[0]["slice0"].avg_ipm == 12.5

    def test_missing_file_yields_defaults(self, tmp_path):
        cfg = load_config(str(tmp_path / "nope.json"))
        assert cfg == ConfigModel()

    def test_corrupt_file_quarantined(self, tmp_path):
        path = str(tmp_path / "cfg.json")
        with open(path, "w") as f:
            f.write("{not json")
        cfg = load_config(path)
        assert cfg == ConfigModel()
        assert not os.path.exists(path)  # moved aside
        assert any("corrupt" in p for p in os.listdir(tmp_path))

    def test_legacy_list_migration(self, tmp_path):
        path = str(tmp_path / "workers.json")
        with open(path, "w") as f:
            json.dump([{"label": "gpu1", "address": "host1", "port": 7861}], f)
        cfg = load_config(path)
        assert cfg.workers[0]["gpu1"].address == "host1"

    def test_legacy_list_with_bad_entry_quarantined(self, tmp_path):
        # A non-dict entry in a legacy list must quarantine, not crash
        # (ADVICE r1: migration was outside the try/except).
        path = str(tmp_path / "workers.json")
        with open(path, "w") as f:
            json.dump([{"label": "ok", "address": "host1"}, "not-a-dict"], f)
        cfg = load_config(path)
        assert cfg == ConfigModel()
        assert any("invalid" in p for p in os.listdir(tmp_path))

    def test_reference_format_config_accepted(self, tmp_path):
        # A reference-era distributed-config.json carries worker fields this
        # schema doesn't define (`state`) and the -1 pixel_cap sentinel
        # (reference pmodels.py:12-34). It must load, not quarantine
        # (VERDICT r1 weak #5).
        path = str(tmp_path / "cfg.json")
        ref_cfg = {
            "workers": [
                {
                    "laptop": {
                        "address": "192.168.1.3",
                        "port": 7860,
                        "avg_ipm": 4.2,
                        "master": False,
                        "eta_percent_error": [1.5, -2.0],
                        "user": None,
                        "password": None,
                        "tls": False,
                        "state": 1,
                        "disabled": False,
                        "pixel_cap": -1,
                    }
                }
            ],
            "benchmark_payload": {
                "prompt": "A herd of cows grazing at the bottom of a sunny valley",
                "negative_prompt": "",
                "steps": 20,
                "width": 512,
                "height": 512,
                "batch_size": 1,
            },
            "job_timeout": 3,
            "enabled": True,
            "enabled_i2i": True,
            "complement_production": True,
            "step_scaling": False,
        }
        with open(path, "w") as f:
            json.dump(ref_cfg, f)
        cfg = load_config(path)
        assert os.path.exists(path)  # not quarantined
        w = cfg.workers[0]["laptop"]
        assert w.avg_ipm == 4.2
        assert w.pixel_cap == 0  # -1 sentinel normalized to uncapped

    def test_defaults_parity_with_reference(self):
        cfg = ConfigModel()
        assert cfg.enabled_i2i is True  # reference pmodels.py:44


@pytest.mark.slow
class TestRng:
    """The seed contract: image i depends only on (seed + i) — the reference's
    seed-offset fan-out (distributed.py:297-305) reproduced exactly.

    (marked slow: the sub-batch/seed-resize cases jit real noise pipelines,
    ~30 s of the module's wall time)"""

    def test_subbatch_equals_full_batch(self):
        shape = (4, 8, 8)
        full = rng.batch_noise(123, 0, 0.0, 0, 6, shape)
        part = rng.batch_noise(123, 0, 0.0, 4, 2, shape)
        np.testing.assert_array_equal(np.asarray(full[4:6]), np.asarray(part))

    def test_offset_seed_equivalence(self):
        # Worker B starting at index 3 of seed 100 == fresh request seeded 103.
        shape = (2, 4, 4)
        a = rng.batch_noise(100, 0, 0.0, 3, 1, shape)
        b = rng.batch_noise(103, 0, 0.0, 0, 1, shape)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_seed_resize_pastes_centered(self):
        # webui seed-resize: noise drawn at the "from" latent size lands
        # centered in the target; the uncovered border stays zero.
        shape = (8, 8, 4)
        src = rng.batch_noise(42, 0, 0.0, 0, 2, (4, 4, 4))
        out = rng.batch_noise(42, 0, 0.0, 0, 2, shape, seed_resize=(4, 4))
        np.testing.assert_array_equal(
            np.asarray(out[:, 2:6, 2:6]), np.asarray(src))
        border = np.asarray(out).copy()
        border[:, 2:6, 2:6] = 0
        assert not border.any()
        # larger-than-target from-size: the CENTER of the source is kept
        big = rng.batch_noise(42, 0, 0.0, 0, 2, (8, 8, 4))
        crop = rng.batch_noise(42, 0, 0.0, 0, 2, (4, 4, 4),
                               seed_resize=(8, 8))
        np.testing.assert_array_equal(
            np.asarray(big[:, 2:6, 2:6]), np.asarray(crop))
        # sub-batch contract survives seed-resize
        part = rng.batch_noise(42, 0, 0.0, 1, 1, shape, seed_resize=(4, 4))
        np.testing.assert_array_equal(np.asarray(out[1:2]), np.asarray(part))

    def test_different_seeds_differ(self):
        shape = (2, 4, 4)
        a = rng.noise_for_image(1, 0, 0.0, 0, shape)
        b = rng.noise_for_image(2, 0, 0.0, 0, shape)
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_subseed_blend(self):
        shape = (2, 4, 4)
        base = rng.noise_for_image(1, 999, 0.0, 0, shape)
        blended = rng.noise_for_image(1, 999, 0.5, 0, shape)
        pure_sub = rng.noise_for_image(999, 0, 0.0, 0, shape)
        assert not np.array_equal(np.asarray(base), np.asarray(blended))
        assert not np.array_equal(np.asarray(pure_sub), np.asarray(blended))
        # strength 0 reproduces the base exactly
        again = rng.noise_for_image(1, 999, 0.0, 0, shape)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(again))

    def test_variation_batch_shares_base_noise(self):
        # webui/reference contract (distributed.py:297-305): with
        # subseed_strength > 0 the base seed does NOT advance per image —
        # only the subseed does. Images at different indices must converge
        # to the SAME base noise as strength -> 0.
        shape = (2, 4, 4)
        eps = 1e-4
        near0_idx0 = rng.noise_for_image(7, 99, eps, 0, shape)
        near0_idx3 = rng.noise_for_image(7, 99, eps, 3, shape)
        base = rng.noise_for_image(7, 99, 0.0, 0, shape)
        np.testing.assert_allclose(
            np.asarray(near0_idx0), np.asarray(base), atol=1e-2
        )
        np.testing.assert_allclose(
            np.asarray(near0_idx3), np.asarray(base), atol=1e-2
        )
        # while at real strength the subseed component still varies by index
        s_idx0 = rng.noise_for_image(7, 99, 0.5, 0, shape)
        s_idx3 = rng.noise_for_image(7, 99, 0.5, 3, shape)
        assert not np.array_equal(np.asarray(s_idx0), np.asarray(s_idx3))

    def test_jittable_with_traced_seed(self):
        import jax

        f = jax.jit(lambda s: rng.noise_for_image(s, 0, 0.0, 0, (2, 2)))
        a, b = f(jnp.uint32(5)), f(jnp.uint32(6))
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_slerp_endpoints(self):
        a = jnp.ones((8,))
        b = -jnp.ones((8,)) + 0.1
        np.testing.assert_allclose(np.asarray(rng.slerp(0.0, a, b)), np.asarray(a), atol=1e-6)
        np.testing.assert_allclose(np.asarray(rng.slerp(1.0, a, b)), np.asarray(b), atol=1e-5)


class TestLogging:
    def test_ring_buffer(self):
        logger = configure(debug=True, use_rich=False)
        ring = get_ring_buffer()
        ring.clear()
        for i in range(20):
            logger.info("msg %d", i)
        lines = ring.dump()
        assert len(lines) == 16  # capacity parity with shared.py:44
        assert lines[-1].endswith("msg 19")
        assert lines[0].endswith("msg 4")


class TestBatchKeys:
    """rng.batch_keys carries the sampler-key seed discipline (start-offset
    continuity + variation pinning) that engine._image_keys delegates to —
    pinned here against the eager per-image form."""

    def test_subrange_matches_full(self):
        import numpy as np

        full = rng.batch_keys(1234, 0, 6)
        sub = rng.batch_keys(1234, 2, 3)
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(full))[2:5],
            np.asarray(jax.random.key_data(sub)))

    def test_matches_eager_key_for_image(self):
        import numpy as np

        keys = rng.batch_keys(77, 3, 2)
        for j, i in enumerate((3, 4)):
            np.testing.assert_array_equal(
                np.asarray(jax.random.key_data(keys))[j],
                np.asarray(jax.random.key_data(rng.key_for_image(77, i))))

    def test_pin_index_fixes_every_key(self):
        import numpy as np

        keys = np.asarray(jax.random.key_data(
            rng.batch_keys(9, 5, 4, pin_index=True)))
        base = np.asarray(jax.random.key_data(rng.key_for_image(9, 0)))
        for row in keys:
            np.testing.assert_array_equal(row, base)

    def test_full_uint32_seed_range(self):
        rng.batch_keys(2 ** 32 - 1, 0, 2)  # must not overflow

"""Prompt grammar tests: emphasis parsing, chunking, engine integration."""

import numpy as np
import pytest

from stable_diffusion_webui_distributed_tpu.models.prompt import (
    parse_prompt_attention,
    tokenize_weighted,
    pad_chunks,
)
from stable_diffusion_webui_distributed_tpu.models.tokenizer import (
    FallbackTokenizer,
)
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)

from test_pipeline import init_params


class TestParse:
    def test_plain(self):
        assert parse_prompt_attention("a cow") == [("a cow", 1.0)]

    def test_round_brackets(self):
        out = parse_prompt_attention("a (cat) walks")
        assert out == [("a ", 1.0), ("cat", 1.1), (" walks", 1.0)]

    def test_explicit_weight(self):
        out = parse_prompt_attention("(cat:1.3)")
        assert out == [("cat", pytest.approx(1.3))]

    def test_square_brackets(self):
        out = parse_prompt_attention("[dog]")
        assert out == [("dog", pytest.approx(1 / 1.1))]

    def test_nested(self):
        out = parse_prompt_attention("((cat))")
        assert out == [("cat", pytest.approx(1.1 * 1.1))]

    def test_escapes(self):
        out = parse_prompt_attention(r"a \(literal\) x")
        assert "".join(s for s, _ in out) == "a (literal) x"
        assert all(w == 1.0 for _, w in out)

    def test_unclosed_bracket(self):
        out = parse_prompt_attention("(cat")
        assert out == [("cat", pytest.approx(1.1))]

    def test_break(self):
        out = parse_prompt_attention("a BREAK b")
        assert ("BREAK", -1.0) in [tuple(x) for x in out]


class TestTokenizeWeighted:
    def test_short_prompt_single_chunk(self):
        tok = FallbackTokenizer(1024)
        ids, w = tokenize_weighted(tok, "a (cow:1.5) here")
        assert ids.shape == (1, 77) and w.shape == (1, 77)
        assert ids[0, 0] == tok.bos
        assert 1.5 in w  # emphasized token carries its weight
        assert w[0, 0] == 1.0  # BOS weight untouched

    def test_long_prompt_chunks(self):
        tok = FallbackTokenizer(1024)
        prompt = " ".join(f"word{i}" for i in range(150))
        ids, w = tokenize_weighted(tok, prompt)
        assert ids.shape[0] == 2  # 150 tokens -> two 75-content chunks
        assert (ids[:, 0] == tok.bos).all()

    def test_break_forces_chunk(self):
        tok = FallbackTokenizer(1024)
        ids, _ = tokenize_weighted(tok, "left BREAK right")
        assert ids.shape[0] == 2

    def test_pad_chunks(self):
        tok = FallbackTokenizer(1024)
        a, wa = tokenize_weighted(tok, "short")
        b, wb = pad_chunks(a, wa, 3, tok.eos, tok.bos)
        assert b.shape == (3, 77)
        assert (b[1:, 0] == tok.bos).all()
        assert (wb[1:] == 1.0).all()


class TestEngineIntegration:
    @pytest.fixture(scope="class")
    def engine(self):
        return Engine(TINY, init_params(TINY), chunk_size=4,
                      state=GenerationState())

    def test_emphasis_changes_output(self, engine):
        base = engine.txt2img(GenerationPayload(
            prompt="a red cow", steps=3, width=32, height=32, seed=2))
        emph = engine.txt2img(GenerationPayload(
            prompt="a (red:1.8) cow", steps=3, width=32, height=32, seed=2))
        assert base.images[0] != emph.images[0]

    def test_weight_one_parens_is_identity(self, engine):
        base = engine.txt2img(GenerationPayload(
            prompt="a red cow", steps=3, width=32, height=32, seed=2))
        same = engine.txt2img(GenerationPayload(
            prompt="a (red:1.0) cow", steps=3, width=32, height=32, seed=2))
        assert base.images[0] == same.images[0]

    def test_long_prompt_generates(self, engine):
        prompt = "a cow " + " ".join(f"detail{i}" for i in range(120))
        r = engine.txt2img(GenerationPayload(
            prompt=prompt, steps=3, width=32, height=32, seed=4))
        assert len(r.images) == 1

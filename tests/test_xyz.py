"""X/Y/Z plot: axis parsing, cell fan-out, grid assembly (pipeline/xyz.py)."""

import numpy as np
import pytest

from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
    GenerationResult,
    array_to_b64png,
    b64png_to_array,
)
from stable_diffusion_webui_distributed_tpu.pipeline import xyz


class TestParse:
    def test_comma_list_int(self):
        assert xyz.parse_axis_values("int", "10, 20,30") == [10, 20, 30]

    def test_plain_int_range(self):
        assert xyz.parse_axis_values("int", "1-5") == [1, 2, 3, 4, 5]

    def test_counted_range(self):
        assert xyz.parse_axis_values("int", "1-10 [5]") == [1, 3, 5, 7, 10]

    def test_counted_range_float(self):
        vals = xyz.parse_axis_values("float", "0-1 [3]")
        assert vals == [0.0, 0.5, 1.0]

    def test_stepped_range(self):
        assert xyz.parse_axis_values("int", "1-10 (+2)") == [1, 3, 5, 7, 9]

    def test_descending_int_range(self):
        assert xyz.parse_axis_values("int", "3-1") == [3, 2, 1]

    def test_text_list(self):
        assert xyz.parse_axis_values("text", "Euler a, DDIM") == \
            ["Euler a", "DDIM"]

    def test_empty_is_single_none(self):
        assert xyz.parse_axis_values("none", "") == [None]
        assert xyz.parse_axis_values("int", "") == [None]

    def test_zero_step_raises(self):
        with pytest.raises(ValueError):
            xyz.parse_axis_values("int", "1-5 (+0)")


def _stub_execute(log):
    def execute(p):
        log.append(p)
        img = np.full((8, 8, 3), len(log) * 10 % 255, np.uint8)
        return GenerationResult(
            images=[array_to_b64png(img)], seeds=[p.seed], subseeds=[0],
            prompts=[p.prompt], negative_prompts=[p.negative_prompt],
            infotexts=[f"Steps: {p.steps}"], worker_labels=[""])
    return execute


class TestRun:
    def test_grid_and_cells(self):
        log = []
        p = GenerationPayload(
            prompt="a cat", seed=4, steps=20,
            script_name="x/y/z plot",
            script_args=[{"x_axis": "Steps", "x_values": "10,20",
                          "y_axis": "CFG Scale", "y_values": "5,7,9"}])
        out = xyz.run_xyz(p, _stub_execute(log))
        assert len(log) == 6  # 2 x 3 cells
        assert sorted({c.steps for c in log}) == [10, 20]
        assert sorted({c.cfg_scale for c in log}) == [5.0, 7.0, 9.0]
        # every cell shares the fixed base seed
        assert {c.seed for c in log} == {4}
        # gallery: 1 grid + 6 cells, grid first
        assert len(out.images) == 7
        grid = b64png_to_array(out.images[0])
        # 2 cols x 3 rows of 8x8 cells + label margins
        assert grid.shape[0] >= 24 and grid.shape[1] >= 16

    def test_prompt_sr(self):
        log = []
        p = GenerationPayload(
            prompt="a red cat", seed=1, script_name="xyz plot",
            script_args=[{"x_axis": "Prompt S/R",
                          "x_values": "red, blue, green"}])
        xyz.run_xyz(p, _stub_execute(log))
        assert [c.prompt for c in log] == \
            ["a red cat", "a blue cat", "a green cat"]

    def test_seed_axis_overrides_base(self):
        log = []
        p = GenerationPayload(
            prompt="x", seed=7, script_name="x/y/z plot",
            script_args=[{"x_axis": "Seed", "x_values": "100,200"}])
        xyz.run_xyz(p, _stub_execute(log))
        assert [c.seed for c in log] == [100, 200]

    def test_unknown_axis_and_cap(self):
        p = GenerationPayload(
            prompt="x", script_name="x/y/z plot",
            script_args=[{"x_axis": "nope", "x_values": "1"}])
        with pytest.raises(ValueError):
            xyz.run_xyz(p, _stub_execute([]))
        p2 = GenerationPayload(
            prompt="x", script_name="x/y/z plot",
            script_args=[{"x_axis": "Seed", "x_values": "1-200"}])
        with pytest.raises(ValueError):
            xyz.run_xyz(p2, _stub_execute([]))

    def test_unknown_sampler_rejected(self):
        p = GenerationPayload(
            prompt="x", script_name="x/y/z plot",
            script_args=[{"x_axis": "Sampler", "x_values": "Euler a, Bogus"}])
        with pytest.raises(ValueError):
            xyz.run_xyz(p, _stub_execute([]), known_samplers=["Euler a"])

    def test_z_axis_multiple_grids(self):
        log = []
        p = GenerationPayload(
            prompt="x", seed=1, script_name="x/y/z plot",
            script_args=[{"x_axis": "Steps", "x_values": "10,20",
                          "z_axis": "CFG Scale", "z_values": "5,9"}])
        out = xyz.run_xyz(p, _stub_execute(log))
        assert len(log) == 4
        assert len(out.images) == 6  # 2 grids + 4 cells

    def test_cells_are_full_requests_not_mutations(self):
        """The base payload must not leak mutations between cells."""
        log = []
        p = GenerationPayload(
            prompt="a red cat", seed=1, script_name="x/y/z plot",
            script_args=[{"x_axis": "Prompt S/R",
                          "x_values": "red, blue"},
                         {"y_axis": "Steps", "y_values": "10,20"}])
        xyz.run_xyz(p, _stub_execute(log))
        prompts = [c.prompt for c in log]
        assert prompts == ["a red cat", "a blue cat"] * 2

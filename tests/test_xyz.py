"""X/Y/Z plot: axis parsing, cell fan-out, grid assembly (pipeline/xyz.py)."""

import numpy as np
import pytest

from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
    GenerationResult,
    array_to_b64png,
    b64png_to_array,
)
from stable_diffusion_webui_distributed_tpu.pipeline import xyz


class TestParse:
    def test_comma_list_int(self):
        assert xyz.parse_axis_values("int", "10, 20,30") == [10, 20, 30]

    def test_plain_int_range(self):
        assert xyz.parse_axis_values("int", "1-5") == [1, 2, 3, 4, 5]

    def test_counted_range(self):
        assert xyz.parse_axis_values("int", "1-10 [5]") == [1, 3, 5, 7, 10]

    def test_counted_range_float(self):
        vals = xyz.parse_axis_values("float", "0-1 [3]")
        assert vals == [0.0, 0.5, 1.0]

    def test_stepped_range(self):
        assert xyz.parse_axis_values("int", "1-10 (+2)") == [1, 3, 5, 7, 9]

    def test_descending_int_range(self):
        assert xyz.parse_axis_values("int", "3-1") == [3, 2, 1]

    def test_text_list(self):
        assert xyz.parse_axis_values("text", "Euler a, DDIM") == \
            ["Euler a", "DDIM"]

    def test_empty_is_single_none(self):
        assert xyz.parse_axis_values("none", "") == [None]
        assert xyz.parse_axis_values("int", "") == [None]

    def test_zero_step_raises(self):
        with pytest.raises(ValueError):
            xyz.parse_axis_values("int", "1-5 (+0)")


def _stub_execute(log):
    def execute(p):
        log.append(p)
        img = np.full((8, 8, 3), len(log) * 10 % 255, np.uint8)
        return GenerationResult(
            images=[array_to_b64png(img)], seeds=[p.seed], subseeds=[0],
            prompts=[p.prompt], negative_prompts=[p.negative_prompt],
            infotexts=[f"Steps: {p.steps}"], worker_labels=[""])
    return execute


class TestRun:
    def test_grid_and_cells(self):
        log = []
        p = GenerationPayload(
            prompt="a cat", seed=4, steps=20,
            script_name="x/y/z plot",
            script_args=[{"x_axis": "Steps", "x_values": "10,20",
                          "y_axis": "CFG Scale", "y_values": "5,7,9"}])
        out = xyz.run_xyz(p, _stub_execute(log))
        assert len(log) == 6  # 2 x 3 cells
        assert sorted({c.steps for c in log}) == [10, 20]
        assert sorted({c.cfg_scale for c in log}) == [5.0, 7.0, 9.0]
        # every cell shares the fixed base seed
        assert {c.seed for c in log} == {4}
        # gallery: 1 grid + 6 cells, grid first
        assert len(out.images) == 7
        grid = b64png_to_array(out.images[0])
        # 2 cols x 3 rows of 8x8 cells + label margins
        assert grid.shape[0] >= 24 and grid.shape[1] >= 16

    def test_prompt_sr(self):
        log = []
        p = GenerationPayload(
            prompt="a red cat", seed=1, script_name="xyz plot",
            script_args=[{"x_axis": "Prompt S/R",
                          "x_values": "red, blue, green"}])
        xyz.run_xyz(p, _stub_execute(log))
        assert [c.prompt for c in log] == \
            ["a red cat", "a blue cat", "a green cat"]

    def test_seed_axis_overrides_base(self):
        log = []
        p = GenerationPayload(
            prompt="x", seed=7, script_name="x/y/z plot",
            script_args=[{"x_axis": "Seed", "x_values": "100,200"}])
        xyz.run_xyz(p, _stub_execute(log))
        assert [c.seed for c in log] == [100, 200]

    def test_unknown_axis_and_cap(self):
        p = GenerationPayload(
            prompt="x", script_name="x/y/z plot",
            script_args=[{"x_axis": "nope", "x_values": "1"}])
        with pytest.raises(ValueError):
            xyz.run_xyz(p, _stub_execute([]))
        p2 = GenerationPayload(
            prompt="x", script_name="x/y/z plot",
            script_args=[{"x_axis": "Seed", "x_values": "1-200"}])
        with pytest.raises(ValueError):
            xyz.run_xyz(p2, _stub_execute([]))

    def test_unknown_sampler_rejected(self):
        p = GenerationPayload(
            prompt="x", script_name="x/y/z plot",
            script_args=[{"x_axis": "Sampler", "x_values": "Euler a, Bogus"}])
        with pytest.raises(ValueError):
            xyz.run_xyz(p, _stub_execute([]), known_samplers=["Euler a"])

    def test_z_axis_multiple_grids(self):
        log = []
        p = GenerationPayload(
            prompt="x", seed=1, script_name="x/y/z plot",
            script_args=[{"x_axis": "Steps", "x_values": "10,20",
                          "z_axis": "CFG Scale", "z_values": "5,9"}])
        out = xyz.run_xyz(p, _stub_execute(log))
        assert len(log) == 4
        assert len(out.images) == 6  # 2 grids + 4 cells

    def test_positional_script_args(self):
        """webui-style flat [x_axis, x_values, y_axis, ...] string list."""
        log = []
        p = GenerationPayload(
            prompt="x", seed=1, script_name="x/y/z plot",
            script_args=["Steps", "10,20", "CFG Scale", "5,7"])
        out = xyz.run_xyz(p, _stub_execute(log))
        assert len(log) == 4
        assert sorted({c.steps for c in log}) == [10, 20]
        assert sorted({c.cfg_scale for c in log}) == [5.0, 7.0]
        assert len(out.images) == 5  # grid + 4 cells

    def test_unusable_script_args_rejected(self):
        # webui-style int dropdown indices: rejected loudly (they index an
        # install-specific AxisOption list), never silently mis-aligned
        p = GenerationPayload(
            prompt="x", script_name="x/y/z plot", script_args=[3, 7])
        with pytest.raises(ValueError, match="axis-name/value strings"):
            xyz.run_xyz(p, _stub_execute([]))
        # empty dicts: parsed but yield nothing usable -> still a 422-class
        # error, not a silent single-cell "nothing" plot
        p2 = GenerationPayload(
            prompt="x", script_name="x/y/z plot", script_args=[{}])
        with pytest.raises(ValueError, match="no usable axis options"):
            xyz.run_xyz(p2, _stub_execute([]))

    def test_interrupt_mid_row_returns_partial_grid(self):
        """Interrupting after >=1 full row must still assemble a grid
        (ragged rows used to crash _draw_grid's concatenate)."""
        from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
            GenerationState,
        )

        state = GenerationState()
        log = []
        inner = _stub_execute(log)

        def execute(p):
            res = inner(p)
            if len(log) == 3:  # interrupt mid-second-row of a 2x3 grid
                state.flag.interrupt()
            return res

        p = GenerationPayload(
            prompt="x", seed=1, script_name="x/y/z plot",
            script_args=[{"x_axis": "Steps", "x_values": "10,20",
                          "y_axis": "CFG Scale", "y_values": "5,7,9"}])
        out = xyz.run_xyz(p, execute, state=state)
        assert len(log) == 3  # stopped launching cells
        # grid first, then the 3 completed cells
        assert len(out.images) == 4
        grid = b64png_to_array(out.images[0])
        assert grid.shape[0] >= 16 and grid.shape[1] >= 16

    def test_interrupt_stops_remaining_z_slices(self):
        """The z loop must stop too: each cell's execute() clears the latch
        at its own request scope, so a surviving z loop would run a full
        row per remaining slice after the interrupt."""
        from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
            GenerationState,
        )

        state = GenerationState()
        log = []
        inner = _stub_execute(log)

        def execute(p):
            state.flag.clear()  # like World.execute/begin_request
            res = inner(p)
            if len(log) == 1:
                state.flag.interrupt()
            return res

        p = GenerationPayload(
            prompt="x", seed=1, script_name="x/y/z plot",
            script_args=[{"x_axis": "Steps", "x_values": "10,20",
                          "z_axis": "CFG Scale", "z_values": "5,7,9"}])
        out = xyz.run_xyz(p, execute, state=state)
        assert len(log) == 1  # nothing launched after the interrupt
        assert len(out.images) == 2  # slice-0 partial grid + its one cell

    def test_cells_are_full_requests_not_mutations(self):
        """The base payload must not leak mutations between cells."""
        log = []
        p = GenerationPayload(
            prompt="a red cat", seed=1, script_name="x/y/z plot",
            script_args=[{"x_axis": "Prompt S/R",
                          "x_values": "red, blue"},
                         {"y_axis": "Steps", "y_values": "10,20"}])
        xyz.run_xyz(p, _stub_execute(log))
        prompts = [c.prompt for c in log]
        assert prompts == ["a red cat", "a blue cat"] * 2


class TestStrictArgValidation:
    """Advisor r4: non-string entries must be rejected even after a dict,
    and positional lists longer than the 6 axis keys must raise instead of
    silently dropping the tail."""

    def test_non_string_after_dict_rejected(self):
        p = GenerationPayload(
            prompt="x", script_name="x/y/z plot",
            script_args=[{"x_axis": "Steps", "x_values": "10,20"}, 3])
        with pytest.raises(ValueError, match="axis-name/value strings"):
            xyz.run_xyz(p, _stub_execute([]))

    def test_overlong_positional_rejected(self):
        p = GenerationPayload(
            prompt="x", script_name="x/y/z plot",
            script_args=["Steps", "10", "CFG Scale", "5", "Seed", "1,2",
                         "extra-tail"])
        with pytest.raises(ValueError, match="at most 6 positional"):
            xyz.run_xyz(p, _stub_execute([]))

"""OB002 fixture: ad-hoc metric-name strings outside obs/prometheus.py.

Loaded by tests/test_lint.py under a spoofed package-relative path so the
metricrules pass sees it as package code.
"""

from stable_diffusion_webui_distributed_tpu.obs.prometheus import (
    register_metric,
)

# BAD (line 12): metric-name literal rendered by hand, never registered
LINE = "sdtpu_rogue_total"


def render_adhoc(lines):
    # BAD (line 17): second ad-hoc name, inside a function scope
    lines.append("sdtpu_sneaky_gauge" + " 0")
    return lines


# OK: handed straight to the registry helper
GOOD = register_metric("sdtpu_sanctioned_total", "counter", "fine")

# OK: non-metric identifier opted out with the marker
TOKEN = "sdtpu_not_a_metric"  # sdtpu-lint: metric

"""Known-bad lock-order fixture (LK005, alongside LK003).

Two thread entry points acquire the same pair of locks in opposite
orders — the classic AB/BA deadlock — plus a stale lockorder
annotation that contradicts no derived edge.

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import threading


class Pair:  # line 13: LK003 + LK005 pin here (edge anchored at the class)
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()

    def forward(self):
        with self.a:
            with self.b:  # edge Pair.a -> Pair.b
                pass

    def backward(self):
        with self.b:
            with self.a:  # edge Pair.b -> Pair.a closes the cycle
                pass


def launch():
    pair = Pair()
    threading.Thread(target=pair.forward, daemon=True).start()
    threading.Thread(target=pair.backward, daemon=True).start()


# next line (36) is an LK005 stale annotation — no Ghost lock edges exist
# sdtpu-lint: lockorder Ghost.a<Ghost.b

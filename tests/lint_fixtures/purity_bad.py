"""Known-bad trace-purity fixture (TP001/TP002/TP003).

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import random
import time

import jax
import jax.numpy as jnp


@jax.jit
def wall_clock_leak(x):
    t = time.time()  # TP001: frozen at trace time
    return x * t


@jax.jit
def host_rng_leak(x):
    return x + random.random()  # TP001: one sample baked into the trace


@jax.jit
def branch_on_tracer(x):
    if x > 0:  # TP002: concretizes the tracer
        return x
    return -x


def make_accumulator():
    history = {}

    @jax.jit
    def accumulate(x):
        history["last"] = x  # TP003: runs once at trace time
        return jnp.sum(x)

    return accumulate

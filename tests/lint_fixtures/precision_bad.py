"""Known-bad serving-precision fixture (RC003).

The serving precision is a STATIC compile-key and group-key axis
(pipeline/engine.py chunk key, serving/dispatcher.py:_group_key): a raw
``SDTPU_UNET_INT8`` env read, a raw ``override_settings.get("precision")``
or a raw ``payload.precision`` attribute read bypasses the 3-rung ladder
in pipeline/precision.py — either an unbounded executable key or a
group-key bypass that coalesces int8 and bf16 requests into one
executable. The clean variant routes through ``bucket_precision``.

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
from stable_diffusion_webui_distributed_tpu.pipeline.precision import (
    bucket_precision,
)
from stable_diffusion_webui_distributed_tpu.runtime.config import env_flag


def group_key_bad(payload):
    ov = payload.override_settings or {}
    use_int8 = env_flag("SDTPU_UNET_INT8", False)  # RC003: raw env read
    name = ov.get("precision")  # RC003: raw override read
    raw = payload.precision  # RC003: group-key bypass
    return ("txt2img", use_int8, name, raw)


def group_key_clean(payload):
    name = bucket_precision(payload.precision, "bf16")  # clean: ladder
    return ("txt2img", name)

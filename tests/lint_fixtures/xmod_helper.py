"""Cross-module taint fixture, helper half.

``raw_steps`` launders a request read across a module boundary: the old
intra-procedural pass sees ``raw_steps(payload)`` in the consumer as a
clean call (a bare ``payload`` name is not a taint source; only attribute
reads are), while the summary engine knows the callee returns
``payload.steps``. tests/test_lint.py asserts BOTH behaviors.

Analyzed as AST only — never imported, never run.
"""


def raw_steps(payload):
    return payload.steps


def bucketed_steps(payload):
    return bucket_steps(payload.steps)


def bucket_steps(steps):
    return max(steps, 8)

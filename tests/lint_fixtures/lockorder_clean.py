"""Clean twin of lockorder_bad.py: the same AB/BA shape, with the
inversion annotated away.

The annotation asserts the runtime discipline is ``Pair.a`` before
``Pair.b`` (the order the paired runtime test exercises), which removes
the contradicted static ``Pair.b -> Pair.a`` edge — so neither LK003
nor LK005 fires, and the annotation is not stale.

Analyzed by tests/test_lint.py as AST only — never imported, never run.
"""
import threading


class Pair:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()

    def forward(self):
        with self.a:
            with self.b:
                pass

    def backward(self):
        # sdtpu-lint: lockorder Pair.a<Pair.b
        with self.b:
            with self.a:
                pass


def launch():
    pair = Pair()
    threading.Thread(target=pair.forward, daemon=True).start()
    threading.Thread(target=pair.backward, daemon=True).start()

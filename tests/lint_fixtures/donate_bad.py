"""Known-bad donation fixture (DN001).

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import jax
import jax.numpy as jnp


def use_after_donate(latents, noise):
    fn = jax.jit(lambda c, n: c + n, donate_argnums=(0,))
    out = fn(latents, noise)
    return latents + out  # DN001: latents was donated


def rebind_ok(carry, noise):
    fn = jax.jit(lambda c, n: c + n, donate_argnums=(0,))
    for _ in range(4):
        carry = fn(carry, noise)  # fine: rebound in the same statement
    return carry


def loop_bad(carry, noise):
    fn = jax.jit(lambda c, n: c + n, donate_argnums=(0,))
    total = jnp.zeros(())
    for _ in range(4):
        total = fn(carry, noise)  # DN001: carry dead on iteration 2
    return total


# sdtpu-lint: jitted(donate=0)
def make_step():
    return jax.jit(lambda c, n: c + n, donate_argnums=(0,))


def factory_donate(carry, noise):
    step = make_step()
    out = step(carry, noise)
    return carry * out  # DN001: donated via marked factory


def audited(carry, noise):
    fn = jax.jit(lambda c, n: c + n, donate_argnums=(0,))
    out = fn(carry, noise)
    return carry.shape, out  # sdtpu-lint: donated

"""Known-bad check-then-act fixture (AT001).

Three violation shapes — stale value written back, stale branch gating
a write, and the interprocedural accessor form — plus the sanctioned
fix (re-validate inside the second critical section), which must stay
clean.

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import threading


class Quota:
    def __init__(self):
        self._lock = threading.Lock()
        self._balance = {}  # guarded-by: _lock

    def reserve_value(self, tenant, cost):
        with self._lock:
            bal = self._balance[tenant]
        # the world can move here: another thread may spend the balance
        with self._lock:
            self._balance[tenant] = bal - cost  # line 24: AT001 (value)

    def reserve_branch(self, tenant, cost):
        with self._lock:
            bal = self._balance[tenant]
        if bal >= cost:
            with self._lock:
                self._balance[tenant] = 0  # line 31: AT001 (branch)

    def reserve_ok(self, tenant, cost):
        with self._lock:
            bal = self._balance[tenant]
        del bal  # gave up on the stale read
        with self._lock:
            if self._balance[tenant] >= cost:  # fresh re-read validates
                self._balance[tenant] = self._balance[tenant] - cost


class Meter:
    def __init__(self):
        self._lock = threading.Lock()
        self._used = 0  # guarded-by: _lock

    def used(self):
        with self._lock:
            return self._used

    def set_used(self, value):
        with self._lock:
            self._used = value


def refund(amount):
    meter = Meter()
    u = meter.used()
    meter.set_used(u - amount)  # line 59: AT001 (accessor)

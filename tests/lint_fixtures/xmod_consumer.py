"""Cross-module taint fixture, consumer half (see xmod_helper.py).

Analyzed as AST only — never imported, never run. Line numbers are
asserted exactly; edit with care.
"""
import jax
import jax.numpy as jnp

from tests.lint_fixtures.xmod_helper import bucketed_steps, raw_steps


def render(payload):
    fn = jax.jit(lambda v, steps: v * steps, static_argnums=(1,))
    steps = raw_steps(payload)  # taint laundered through another module
    return fn(jnp.zeros(4), steps)  # RC001: interprocedural only


def render_bucketed(payload):
    fn = jax.jit(lambda v, steps: v * steps, static_argnums=(1,))
    steps = bucketed_steps(payload)  # callee summary says: sanitized
    return fn(jnp.zeros(4), steps)  # fine either way

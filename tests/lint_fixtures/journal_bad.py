"""OB003 fixture: journal event literals outside the registered set.

Loaded by tests/test_lint.py as a standalone module: obs/journal.py is
not in the analyzed set, so the registered-event vocabulary is empty and
every literal emit is flagged unless marker-exempt.
"""

from stable_diffusion_webui_distributed_tpu.obs import journal as obs_journal
from stable_diffusion_webui_distributed_tpu.obs.journal import emit

# BAD (line 12): module-helper emit with an unregistered literal
obs_journal.emit("complete", "rid-1")


def lifecycle(rid):
    # BAD (line 17): aliased helper emit inside a function scope
    emit("dispatchd", rid, worker="w0")
    # BAD (line 19): keyword spelling of the event argument
    obs_journal.JOURNAL.emit(request_id=rid, event="finishd")


def dynamic(rid, name):
    # OK: computed event name — the runtime check covers it
    obs_journal.emit(name, rid)


# OK: deliberate out-of-band literal, marker-exempt
obs_journal.emit("mysterious", "rid-2")  # sdtpu-lint: journal

# OK: a plain string constant that is not a journal emit call at all
NOTE = "completed"

# Chaos-tier vocabulary pin (sim/chaos.py events): these fire here —
# the standalone fixture analyzes with an empty registry — but are
# accepted when analyzed beside obs/journal.py, which is the assertion
# that fault_injected / fault_cleared joined the closed vocabulary.
obs_journal.emit("fault_injected", "chaos-0", kind="kill")
obs_journal.emit("fault_cleared", "chaos-0", kind="kill")

# Alerting-plane vocabulary pin (obs/alerts.py state machine): same
# deal — flagged standalone, accepted beside the real registry.
obs_journal.emit("alert_firing", "alert-slo", rule="slo_burn_fast")
obs_journal.emit("alert_resolved", "alert-slo", rule="slo_burn_fast")

# Delivery/federation-plane vocabulary pin (obs/notify.py +
# obs/federation.py): flagged standalone, accepted beside the registry.
obs_journal.emit("notify_sent", "notify-fleet_error_rate", attempts=1)
obs_journal.emit("notify_failed", "notify-fleet_error_rate", attempts=3)
obs_journal.emit("federation_poll_failed", "federation-w0", worker="w0")

# Push-control-plane vocabulary pin (obs/push.py + obs/notify.py
# overflow): flagged standalone, accepted beside the real registry.
obs_journal.emit("notify_dropped", "notify-slo_burn_fast", channel="page")
obs_journal.emit("push_buffer_evicted", "push-buffer", evicted=3)
obs_journal.emit("push_fallback", "push-w0", worker="w0")

"""Known-bad step-cache cadence fixture (RC001).

The deep-feature refresh cadence is env-derived (SDTPU_DEEPCACHE);
pinning it as a jit STATIC argument mints one executable per distinct
value. It must be quantized onto the cadence ladder first
(stepcache.bucket_cadence — the clean variant below), or travel as
traced data the way the engine's chunk executable actually carries it.

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.pipeline.stepcache import (
    bucket_cadence,
)
from stable_diffusion_webui_distributed_tpu.runtime.config import env_int


def chunk_bad(payload):
    fn = jax.jit(lambda x, cadence: x * cadence, static_argnums=(1,))
    cadence = env_int("SDTPU_DEEPCACHE", 1)
    return fn(jnp.zeros(4), cadence)  # RC001: raw env cadence as static


def chunk_clean(payload):
    fn = jax.jit(lambda x, cadence: x * cadence, static_argnums=(1,))
    cadence = bucket_cadence(env_int("SDTPU_DEEPCACHE", 1))
    return fn(jnp.zeros(4), cadence)  # clean: ladder-quantized

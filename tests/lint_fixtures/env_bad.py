"""Known-bad environment-read fixture (EV001).

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import os


def read_knob():
    return os.environ.get("SDTPU_KNOB", "")  # EV001


def read_flag():
    return os.getenv("SDTPU_FLAG")  # EV001

"""OB004 fixture: alert-rule registration outside obs/alerts.py.

Loaded by tests/test_lint.py under a spoofed package-relative path so
the alertrules pass sees it as package code.
"""

from stable_diffusion_webui_distributed_tpu.obs.alerts import (
    AlertRule, register_rule,
)

# BAD (line 12): direct registration outside the closed registry
register_rule(AlertRule(
    name="rogue_rule", kind="increase", series="rogue_total",
    description="unexercised by the bench recall gate"))


def register_later(rule):
    # BAD (line 19): aliased/indirect spelling inside a function scope
    register_rule(rule)


# OK: constructing a rule without registering it (tests do this freely)
THROWAWAY = AlertRule(name="scratch", kind="anomaly", series="x",
                      description="never registered")

# OK: deliberate plugin-site registration, marker-exempt
register_rule(THROWAWAY)  # sdtpu-lint: alert

# BAD (line 30): severity literal outside the closed page/warn/info set
ROGUE_SEVERITY = AlertRule(
    name="sev", kind="anomaly", series="y",
    description="mistyped severity", severity="critical")

# OK: a valid severity literal on a throwaway rule
PAGED = AlertRule(name="sev_ok", kind="anomaly", series="y",
                  description="valid severity", severity="page")

# OK: deliberate out-of-set severity, marker-exempt plugin site
WEIRD = AlertRule(  # sdtpu-lint: alert
    name="sev_exempt", kind="anomaly", series="y",
    description="plugin severity", severity="fatal")

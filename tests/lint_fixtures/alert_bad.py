"""OB004 fixture: alert-rule registration outside obs/alerts.py.

Loaded by tests/test_lint.py under a spoofed package-relative path so
the alertrules pass sees it as package code.
"""

from stable_diffusion_webui_distributed_tpu.obs.alerts import (
    AlertRule, register_rule,
)

# BAD (line 12): direct registration outside the closed registry
register_rule(AlertRule(
    name="rogue_rule", kind="increase", series="rogue_total",
    description="unexercised by the bench recall gate"))


def register_later(rule):
    # BAD (line 19): aliased/indirect spelling inside a function scope
    register_rule(rule)


# OK: constructing a rule without registering it (tests do this freely)
THROWAWAY = AlertRule(name="scratch", kind="anomaly", series="x",
                      description="never registered")

# OK: deliberate plugin-site registration, marker-exempt
register_rule(THROWAWAY)  # sdtpu-lint: alert

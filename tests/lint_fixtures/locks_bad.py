"""Known-bad lock-discipline fixture (LK001/LK002/LK003).

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0  # guarded-by: _lock
        self.phantom = 0  # guarded-by: _missing

    def bump(self):
        self.total += 1  # LK001: no lock held

    def read(self):
        with self._lock:
            return self.total  # fine


class Inverted:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def ba(self):
        with self._b:
            with self._a:  # LK003: opposite order to ab()
                pass

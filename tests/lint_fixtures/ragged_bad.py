"""Known-bad ragged-dispatch fixture (RC001).

Per-row TRUE lengths are request-derived (the requested height maps to a
valid latent-row prefix): pinning one as a jit STATIC argument mints a
chunk executable per distinct request height — exactly the shape-ladder
explosion ragged dispatch exists to kill. True lengths must travel as
TRACED data (the clean variant below; ops/ragged_attention.py takes them
as an int32 array), with only the bucket shape left static.

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import jax
import jax.numpy as jnp


def chunk_bad(payload):
    fn = jax.jit(lambda x, true_len: x * true_len, static_argnums=(1,))
    true_len = payload.height
    return fn(jnp.zeros(64), true_len)  # RC001: per-row length as static


def chunk_clean(payload):
    fn = jax.jit(lambda x, true_len: x * (jnp.arange(64) < true_len))
    true_len = jnp.asarray(payload.height, jnp.int32)
    return fn(jnp.zeros(64), true_len)  # clean: length rides as traced data

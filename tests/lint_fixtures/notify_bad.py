"""OB005 fixture: outbound network calls in obs/ outside the trio.

Loaded by tests/test_lint.py under a spoofed obs/ rel path: outbound
HTTP from any obs/ module other than federation/notify/stitch bypasses
the SDTPU_OBS_HTTP_TIMEOUT_S bound and must be flagged.
"""

import urllib.request
from urllib.request import urlopen

import requests

# BAD (line 14): module-level urlopen through the package spelling
urllib.request.urlopen("http://example.invalid/internal/metrics")


def fetch(session):
    # BAD (line 19): aliased urlopen inside a function scope
    urlopen("http://example.invalid/internal/tsdb", timeout=1.0)
    # BAD (line 21): requests verb call
    requests.get("http://example.invalid/hook", timeout=1.0)
    # BAD (line 23): session verb call
    session.post("http://example.invalid/hook", json={}, timeout=1.0)


def sanctioned_escape():
    # OK: deliberate site, marker-exempt
    urlopen("http://example.invalid/ok")  # sdtpu-lint: netcall


def not_network(store):
    # OK: a .get on a non-HTTP owner is not an outbound call
    return store.get("queue_wait_p95_s")

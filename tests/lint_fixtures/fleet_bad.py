"""FL001 fixture: lock-bearing fleet classes with unannotated containers.

Analyzed under a spoofed ``stable_diffusion_webui_distributed_tpu/fleet/``
relative path (the rule is path-scoped); never imported.
"""

import collections
import threading


class BadQueue:
    """Has a lock, but its containers carry no guarded-by annotations."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = []                       # FL001: no annotation
        self._tags = {}                          # FL001: no annotation
        self._pending = collections.deque()      # FL001: no annotation
        self._vt = 0.0  # scalar: out of FL001's scope (LK001 territory)

    def push(self, item):
        with self._lock:
            self._entries.append(item)


class GoodQueue:
    """Annotated containers: clean."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = []  # guarded-by: _lock
        self._tags = {}  # guarded-by: _lock

    def push(self, item):
        with self._lock:
            self._entries.append(item)
            self._tags[item] = 1


class PolicyTable:
    """No lock attribute: immutable-after-init, exempt from FL001."""

    def __init__(self):
        self.classes = {"interactive": 8.0}

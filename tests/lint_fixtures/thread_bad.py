"""Known-bad raw-daemon-thread fixture (TH001).

A hand-rolled daemon loop (Thread around a looping target) and a
Thread subclass with a run() loop both fire; a single-shot background
task stays legal.

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import threading


def _poll(halt):
    while not halt.is_set():
        halt.wait(1.0)


def _report():
    pass


def start_poller(halt):
    t = threading.Thread(target=_poll, daemon=True)  # line 23: TH001
    t.start()
    return t


def start_once():
    t = threading.Thread(target=_report, daemon=True)  # clean: no loop
    t.start()
    return t


class Watcher(threading.Thread):  # line 34: TH001 (run loop subclass)
    def __init__(self):
        super().__init__(daemon=True)
        self._halt = threading.Event()

    def run(self):
        while not self._halt.is_set():
            self._halt.wait(1.0)

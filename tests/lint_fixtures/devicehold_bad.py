"""Known-bad blocking-under-lock fixture (LK004).

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import threading
import time

import requests


class Gate:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def convoy(self, out):
        with self._lock:
            time.sleep(0.5)  # LK004: sleep under lock
            out.block_until_ready()  # LK004: device sync under lock

    def _fetch(self):
        return requests.get("http://example/health")

    def indirect(self):
        with self._lock:
            return self._fetch()  # LK004: callee blocks on the network

    def wait_ok(self):
        with self._cv:
            self._cv.wait()  # fine: wait releases the only held lock

    def release_first(self, out):
        with self._lock:
            pass
        out.block_until_ready()  # fine: lock released before blocking

"""Known-bad cross-object lock fixture (LK001/LK003 through inferred
attribute types — no hand-maintained class hints anywhere).

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import threading


class Node:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = "idle"  # guarded-by: _lock


class Registry:
    def __init__(self):
        self._reg_lock = threading.Lock()
        self.node = Node()

    def peek(self):
        return self.node.state  # LK001: Node._lock not held (cross-object)

    def locked_peek(self):
        with self.node._lock:
            return self.node.state  # fine: the owning lock is held

    def nested(self):
        with self._reg_lock:
            with self.node._lock:
                pass


def inverted(reg: Registry):
    with reg.node._lock:
        with reg._reg_lock:  # LK003: opposite order to Registry.nested
            pass

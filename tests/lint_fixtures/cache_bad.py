"""CA001 fixture: payload hashing / key construction outside cache/keys.py.

Loaded by tests/test_lint.py under a serving/ path (outside the
sanctioned cache/keys.py and obs/journal.py modules), so every payload
digest and hand-built cache-key tuple below is flagged unless
marker-exempt.
"""

import hashlib
import json

def result_key(payload):
    # BAD (line 14): payload dump hashed directly — a forked key mint
    return hashlib.sha256(
        json.dumps(payload.model_dump()).encode()).hexdigest()


def embed_key(req):
    # BAD (line 20): prompt attribute digested outside the key module
    return hashlib.md5(req.prompt.encode()).hexdigest()


def lookup(cache, payload):
    # BAD (line 25): hand-built payload key tuple fed to a cache store
    return cache.get((payload.prompt, payload.seed))


def publish(result_store, payload, value):
    # BAD (line 30): same shape on the put side
    result_store.put((payload.negative_prompt, payload.steps), value)


def canonical(payload):
    # OK: keys minted through the sanctioned module
    from stable_diffusion_webui_distributed_tpu.cache import keys

    return keys.result_key(payload, (), "txt2img")


def etag(payload):
    # OK: deliberate non-key digest, marker-exempt
    return hashlib.sha256(payload.prompt.encode())  # sdtpu-lint: cachekey


def file_hash(path):
    # OK: hashing non-payload bytes is not key minting
    with open(path, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


def plain_dict(d, key):
    # OK: tuple key into a non-cache receiver
    return d.get((key, 0))

"""Known-bad traced-LoRA fixture (RC001).

Adapter identity must never shape an executable: under traced serving
(SDTPU_LORA_TRACED) the rank/slot pair is quantized onto the static
ladder (models/lora.py bucket_rank / bucket_slots) and the factor
CONTENTS travel as jit arguments. A request-derived adapter rank pinned
as a jit STATIC argument mints one executable per distinct adapter —
the recompile storm the ladder exists to kill. The ladder-bucketed
variant below must stay clean.

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.models.lora import bucket_rank


def apply_bad(payload):
    fn = jax.jit(lambda x, rank: x * rank, static_argnums=(1,))
    rank = payload.lora_rank
    return fn(jnp.zeros(4), rank)  # RC001: raw adapter rank as static


def apply_clean(payload):
    fn = jax.jit(lambda x, rank: x * rank, static_argnums=(1,))
    rank = bucket_rank(payload.lora_rank)
    return fn(jnp.zeros(4), rank)  # clean: ladder-quantized

"""Known-bad tracer-escape fixture (TP004).

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import jax


class Denoiser:
    def __init__(self):
        self.trace_leak = None
        self.history = []
        self.stats = None

    def run(self, latents):
        def body(x, sigma):
            self.trace_leak = x * sigma  # TP004: tracer stored on self
            self.history.append(sigma)  # TP004: tracer into container
            self.stats = x.shape  # fine: shape is a trace-time constant
            return x - sigma

        return jax.jit(body)(latents, 0.5)

"""OB001 fixture: wall-clock durations where spans measure latency.

Tests load this file twice: once under a spoofed
``stable_diffusion_webui_distributed_tpu/serving/`` rel path (OB001 fires on
the two wall-clock duration reads below) and once under its real
``tests/lint_fixtures/`` path (out of scope -> zero findings).
"""

import time


def bad_duration():
    t0 = time.time()
    work()
    return time.time() - t0


def good_duration():
    t0 = time.perf_counter()
    work()
    return time.perf_counter() - t0


def stamped_entry():
    return {"recorded_at": time.time()}  # sdtpu-lint: wallclock


def work():
    return None

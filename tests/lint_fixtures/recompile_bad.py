"""Known-bad recompile-hazard fixture (RC001/RC002).

Analyzed by tests/test_lint.py as AST only — never imported, never run.
Line numbers are asserted exactly; edit with care.
"""
import jax
import jax.numpy as jnp


def render(payload):
    def denoise(latent, steps):
        return latent * steps

    fn = jax.jit(denoise, static_argnums=(1,))
    steps = payload.steps
    out = fn(jnp.zeros(4), steps)  # RC001: unbounded static from payload
    width = payload.width

    def scaled(x):  # RC002: closes over request-derived 'width'
        return x * width

    return jax.jit(scaled)(out)


# sdtpu-lint: jitted(static=1)
def make_encoder():
    return jax.jit(lambda v, skip: v * skip, static_argnums=(1,))


def handler(request):
    enc = make_encoder()
    skip = request.clip_skip

    def encode_one():
        return enc(jnp.zeros(2), skip)  # RC001: via closure inheritance

    return encode_one

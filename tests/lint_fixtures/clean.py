"""Clean fixture: idioms the analyzer must NOT flag (zero findings).

Analyzed by tests/test_lint.py as AST only — never imported, never run.
"""
import threading

import jax
import jax.numpy as jnp


@jax.jit
def keyed_noise(key, x):
    # jax.random is keyed, deterministic, and trace-safe — never TP001
    return x + jax.random.normal(key, x.shape)


@jax.jit
def shape_branch(x):
    if x.ndim == 3:  # shape introspection is a trace-time constant
        x = x[None]
    if x is None:  # None-checks never concretize a tracer
        return jnp.zeros(())
    return x * 2


def render(payload, bucketer):
    fn = jax.jit(lambda v, s: v * s, static_argnums=(1,))
    steps = min(64, payload.steps)  # constant clamp bounds the key space
    w = bucketer.bucket_shape(payload.width)  # ladder quantization
    return fn(jnp.zeros(4), steps), w


class SafeBox:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []  # guarded-by: _lock

    def add(self, x):
        with self._lock:
            self.items.append(x)

"""W8A8 int8 UNet quality floor (ops/quant.py), on the shared quality
harness (tests/quality.py — the same PSNR/SSIM rig the step-cache tests
use).

The int8 path quantizes the UNet transformer linears dynamically
(``Policy.unet_int8``); like the step-cache levers it trades exactness
for throughput, so the contract is the same shape: pixels may move, but
only within a documented PSNR/SSIM floor against the exact f32 baseline
on the SAME random-weight tiny engine.
"""

import dataclasses

import pytest

import quality
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime import dtypes

#: Quality floors for W8A8 on the tiny family (measured well above this;
#: see PERF.md "FLOP levers" for the production caveats).
PSNR_FLOOR_DB = 20.0
SSIM_FLOOR = 0.6


def _payload():
    return GenerationPayload(prompt="a cow", steps=8, width=32, height=32,
                             batch_size=2, seed=42)


@pytest.fixture(scope="module")
def baseline():
    return quality.make_engine(TINY).txt2img(_payload())


@pytest.fixture(scope="module")
def int8_result():
    policy = dataclasses.replace(dtypes.F32, unet_int8=True)
    return quality.make_engine(TINY, policy=policy).txt2img(_payload())


@pytest.mark.slow
class TestInt8Quality:
    def test_int8_actually_engaged(self, baseline, int8_result):
        # identical bytes would mean the quantized path silently no-opped
        assert int8_result.images != baseline.images

    def test_psnr_floor(self, baseline, int8_result):
        db = quality.mean_psnr(int8_result.images, baseline.images)
        assert db >= PSNR_FLOOR_DB, f"int8 PSNR {db:.2f} dB under floor"

    def test_ssim_floor(self, baseline, int8_result):
        s = quality.mean_ssim(int8_result.images, baseline.images)
        assert s >= SSIM_FLOOR, f"int8 SSIM {s:.3f} under floor"


# -- fast tier: per-request precision on ONE default engine ------------------
# The serving-mode contract (pipeline/precision.py): a single engine built
# with env defaults serves ``precision="int8"`` requests from a quantized
# module variant sharing the SAME param tree. Small payload (steps=4,
# batch=1) keeps this in the fast tier; the slow class above keeps the
# deeper 8-step sweep.

def _fast_payload(**kw):
    return GenerationPayload(prompt="a cow", steps=4, width=32, height=32,
                             batch_size=1, seed=42, **kw)


@pytest.fixture(scope="module")
def shared_engine():
    return quality.make_engine(TINY)


@pytest.fixture(scope="module")
def fast_baseline(shared_engine):
    return shared_engine.txt2img(_fast_payload())


@pytest.fixture(scope="module")
def fast_int8(shared_engine):
    return shared_engine.txt2img(_fast_payload(precision="int8"))


class TestInt8PerRequest:
    def test_engaged_and_default_untouched(self, shared_engine,
                                           fast_baseline, fast_int8):
        # the override engaged (different pixels), and re-running the
        # default payload afterwards is byte-identical — the int8 variant
        # never leaks into the bf16 executable
        assert fast_int8.images != fast_baseline.images
        again = shared_engine.txt2img(_fast_payload())
        assert again.images == fast_baseline.images

    def test_psnr_floor(self, fast_baseline, fast_int8):
        db = quality.mean_psnr(fast_int8.images, fast_baseline.images)
        assert db >= PSNR_FLOOR_DB, f"int8 PSNR {db:.2f} dB under floor"

    def test_ssim_floor(self, fast_baseline, fast_int8):
        s = quality.mean_ssim(fast_int8.images, fast_baseline.images)
        assert s >= SSIM_FLOOR, f"int8 SSIM {s:.3f} under floor"

"""W8A8 int8 UNet quality floor (ops/quant.py), on the shared quality
harness (tests/quality.py — the same PSNR/SSIM rig the step-cache tests
use).

The int8 path quantizes the UNet transformer linears dynamically
(``Policy.unet_int8``); like the step-cache levers it trades exactness
for throughput, so the contract is the same shape: pixels may move, but
only within a documented PSNR/SSIM floor against the exact f32 baseline
on the SAME random-weight tiny engine.
"""

import dataclasses

import pytest

import quality
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime import dtypes

#: Quality floors for W8A8 on the tiny family (measured well above this;
#: see PERF.md "FLOP levers" for the production caveats).
PSNR_FLOOR_DB = 20.0
SSIM_FLOOR = 0.6


def _payload():
    return GenerationPayload(prompt="a cow", steps=8, width=32, height=32,
                             batch_size=2, seed=42)


@pytest.fixture(scope="module")
def baseline():
    return quality.make_engine(TINY).txt2img(_payload())


@pytest.fixture(scope="module")
def int8_result():
    policy = dataclasses.replace(dtypes.F32, unet_int8=True)
    return quality.make_engine(TINY, policy=policy).txt2img(_payload())


@pytest.mark.slow
class TestInt8Quality:
    def test_int8_actually_engaged(self, baseline, int8_result):
        # identical bytes would mean the quantized path silently no-opped
        assert int8_result.images != baseline.images

    def test_psnr_floor(self, baseline, int8_result):
        db = quality.mean_psnr(int8_result.images, baseline.images)
        assert db >= PSNR_FLOOR_DB, f"int8 PSNR {db:.2f} dB under floor"

    def test_ssim_floor(self, baseline, int8_result):
        s = quality.mean_ssim(int8_result.images, baseline.images)
        assert s >= SSIM_FLOOR, f"int8 SSIM {s:.3f} under floor"

"""Tests for runtime/daemon.py (StoppableDaemon), the one daemon-loop
base every background thread in the package now rides on (TSDB sampler,
federation prober, notifier drain, heartbeat, watchdog timer — enforced
by lint rule TH001).

Real-thread lifecycle tests keep periods tiny and always stop in a
finally; the schedule-explorer coverage of the stop/restart race lives
in tests/test_sched.py (daemon_restart harness)."""

import threading
import time

from stable_diffusion_webui_distributed_tpu.runtime.daemon import (
    StoppableDaemon,
)


def _wait_until(pred, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return pred()


class TestLifecycle:
    def test_start_runs_ticks_and_stop_joins(self):
        hits = []
        d = StoppableDaemon("t-sampler", lambda: hits.append(1), 0.005)
        try:
            assert d.start()
            assert _wait_until(lambda: len(hits) >= 3)
            assert d.alive()
        finally:
            assert d.stop(timeout_s=5.0)
        assert not d.alive()
        n = len(hits)
        time.sleep(0.05)
        assert len(hits) == n  # no tick after stop returned

    def test_start_is_idempotent(self):
        d = StoppableDaemon("t-idem", lambda: None, 0.005)
        try:
            d.start()
            first = d._thread
            d.start()
            assert d._thread is first  # no second loop thread spawned
        finally:
            d.stop(timeout_s=5.0)

    def test_restart_after_stop_spawns_a_fresh_loop(self):
        hits = []
        d = StoppableDaemon("t-restart", lambda: hits.append(1), 0.005)
        try:
            d.start()
            assert _wait_until(lambda: hits)
            assert d.stop(timeout_s=5.0)
            assert d.stopped()
            n = len(hits)
            d.start()
            assert not d.stopped()  # restart clears the halt flag
            assert _wait_until(lambda: len(hits) > n)
        finally:
            d.stop(timeout_s=5.0)

    def test_stop_never_started_is_a_noop(self):
        d = StoppableDaemon("t-cold", lambda: None, 0.005)
        assert d.stop() is True
        assert not d.alive()

    def test_halt_signals_without_joining(self):
        entered = threading.Event()
        release = threading.Event()

        def tick():
            entered.set()
            release.wait(5.0)

        d = StoppableDaemon("t-halt", tick, 0.005)
        try:
            d.start()
            assert entered.wait(5.0)
            t0 = time.monotonic()
            d.halt()  # must return immediately, mid-tick
            assert time.monotonic() - t0 < 0.5
            assert d.stopped()
        finally:
            release.set()
            d.stop(timeout_s=5.0)

    def test_tick_may_halt_its_own_loop(self):
        hits = []
        d = StoppableDaemon("t-self", lambda: (hits.append(1),
                                               d.halt()), 0.005)
        try:
            d.start()
            assert _wait_until(lambda: not d.alive())
            assert hits == [1]  # halted itself after exactly one tick
        finally:
            d.stop(timeout_s=5.0)


class TestTickPlumbing:
    def test_inline_tick_needs_no_thread(self):
        hits = []
        d = StoppableDaemon("t-inline", lambda: hits.append(1) or 7, 60.0)
        assert d.tick() == 7
        assert hits == [1]
        assert not d.alive()

    def test_wake_cuts_the_pause_short(self):
        hits = []
        d = StoppableDaemon("t-wake", lambda: hits.append(1), 60.0,
                            immediate=False)
        try:
            d.start()
            time.sleep(0.02)
            assert not hits  # parked in the 60s pause
            d.wake()
            assert _wait_until(lambda: hits)
        finally:
            d.stop(timeout_s=5.0)

    def test_callable_period_is_reread_each_iteration(self):
        periods = []

        def period():
            periods.append(1)
            return 0.005

        d = StoppableDaemon("t-knob", lambda: None, period)
        try:
            d.start()
            assert _wait_until(lambda: len(periods) >= 2)
        finally:
            d.stop(timeout_s=5.0)

    def test_immediate_false_pauses_before_first_tick(self):
        hits = []
        d = StoppableDaemon("t-heartbeat", lambda: hits.append(1), 60.0,
                            immediate=False)
        try:
            d.start()
            time.sleep(0.02)
            assert not hits
        finally:
            d.stop(timeout_s=5.0)


class TestOneShot:
    def test_fires_once_after_delay(self):
        hits = []
        d = StoppableDaemon.one_shot("t-timer", 0.01, lambda: hits.append(1))
        try:
            d.start()
            assert _wait_until(lambda: hits)
            assert _wait_until(lambda: not d.alive())
            assert hits == [1]
        finally:
            d.stop(timeout_s=5.0)

    def test_stop_before_expiry_cancels(self):
        hits = []
        d = StoppableDaemon.one_shot("t-wd", 60.0, lambda: hits.append(1))
        d.start()
        assert d.stop(timeout_s=5.0)
        assert not hits
        assert d.stopped()  # the watchdog reads this as "cancelled"

    def test_halt_disarms_without_join(self):
        hits = []
        d = StoppableDaemon.one_shot("t-disarm", 0.05,
                                     lambda: hits.append(1))
        try:
            d.start()
            d.halt()  # obs/watchdog.disarm: signal only, hot path
            assert _wait_until(lambda: not d.alive())
            assert not hits
        finally:
            d.stop(timeout_s=5.0)


class TestErrorPropagation:
    def test_tick_exception_kills_the_loop_loudly(self):
        """The loop never swallows tick exceptions: a poisoned tick must
        end the daemon (dying loudly beats spinning on bad state)."""
        caught = []
        prev_hook = threading.excepthook
        threading.excepthook = lambda args: caught.append(args.exc_type)

        def tick():
            raise RuntimeError("poisoned state")

        d = StoppableDaemon("t-boom", tick, 0.005)
        try:
            d.start()
            assert _wait_until(lambda: not d.alive())
            assert caught == [RuntimeError]  # exactly one tick ran
        finally:
            threading.excepthook = prev_hook
            d.stop(timeout_s=5.0)

"""sdapi-v1 server tests: every route the reference consumes
(/root/reference/scripts/spartan/worker.py:192-203), driven over real HTTP
against a stub world, plus auth and the HTTPBackend client closing the loop
(this framework's own World driving this framework's own server)."""

import json
import urllib.error
import urllib.request

import pytest

from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime.config import ConfigModel
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
    HTTPBackend, StubBackend, WorkerNode,
)
from stable_diffusion_webui_distributed_tpu.scheduler.world import World
from stable_diffusion_webui_distributed_tpu.server.api import ApiServer


def make_world():
    w = World(ConfigModel())
    w.add_worker(WorkerNode("m", StubBackend(), master=True, avg_ipm=10.0))
    return w


@pytest.fixture(scope="module")
def server():
    state = GenerationState()
    srv = ApiServer(make_world(), state=state, host="127.0.0.1", port=0)
    srv.start()
    yield srv
    srv.stop()


def call(server, route, body=None, method=None, headers=None):
    url = f"http://127.0.0.1:{server.port}{route}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method or ("POST" if data else "GET"),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read() or b"{}")


class TestRoutes:
    def test_txt2img(self, server):
        out = call(server, "/sdapi/v1/txt2img",
                   {"prompt": "cow", "batch_size": 2, "seed": 50,
                    "steps": 4, "width": 64, "height": 64})
        assert len(out["images"]) == 2
        info = json.loads(out["info"])
        assert info["all_seeds"] == [50, 51]
        assert info["seed"] == 50

    def test_img2img_requires_init(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            call(server, "/sdapi/v1/img2img", {"prompt": "x"})
        assert e.value.code == 422

    def test_prompt_matrix_over_cap_is_422(self, server):
        # 11 options -> over the 2^10 combination cap: client error, not
        # a 500 from deep inside the engine
        with pytest.raises(urllib.error.HTTPError) as e:
            call(server, "/sdapi/v1/txt2img",
                 {"prompt": "base|" + "|".join(f"o{i}" for i in range(11)),
                  "script_name": "prompt matrix", "steps": 1,
                  "width": 64, "height": 64})
        assert e.value.code == 422

    def test_progress(self, server):
        out = call(server, "/sdapi/v1/progress")
        assert {"progress", "eta_relative", "state"} <= set(out)

    def test_interrupt(self, server):
        call(server, "/sdapi/v1/interrupt", {})
        assert server.state.flag.interrupted
        server.state.flag.clear()

    def test_memory_shapes(self, server):
        out = call(server, "/sdapi/v1/memory")
        assert "ram" in out and "tpu" in out
        # legacy probe shape the reference reads (worker.py:322-340)
        assert "free" in out["cuda"]["system"]

    def test_sd_models_and_samplers(self, server):
        models = call(server, "/sdapi/v1/sd-models")
        assert isinstance(models, list) and models
        samplers = call(server, "/sdapi/v1/samplers")
        names = {s["name"] for s in samplers}
        assert {"Euler a", "DPM++ 2M Karras"} <= names

    def test_script_info_advertises_controlnet(self, server):
        info = call(server, "/sdapi/v1/script-info")
        assert any(s["name"] == "controlnet" for s in info)

    def test_options_roundtrip(self, server):
        call(server, "/sdapi/v1/options", {"CLIP_stop_at_last_layers": 2})
        out = call(server, "/sdapi/v1/options")
        assert out["CLIP_stop_at_last_layers"] == 2

    def test_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            call(server, "/sdapi/v1/nope")
        assert e.value.code == 404

    def test_workers_control_surface(self, server):
        world = server.source
        extra = WorkerNode("r1", StubBackend(), avg_ipm=5.0)
        world.add_worker(extra)
        try:
            # read surface (reference Worker Config tab, ui.py:90-214)
            rows = call(server, "/internal/workers")
            by_label = {r["label"]: r for r in rows}
            assert by_label["r1"]["model_override"] is None
            # write surface: pin + cap round-trip (the pin is validated
            # against the worker's actual model list, ui.py:161-171)
            out = call(server, "/internal/workers",
                       {"label": "r1", "model_override": "stub-model",
                        "pixel_cap": 123456})
            assert out["updated"] == "r1"
            assert extra.model_override == "stub-model"
            assert extra.pixel_cap == 123456
            rows = call(server, "/internal/workers")
            by_label = {r["label"]: r for r in rows}
            assert by_label["r1"]["model_override"] == "stub-model"
            # a pin the worker does not serve -> 422, nothing changed
            with pytest.raises(urllib.error.HTTPError) as e:
                call(server, "/internal/workers",
                     {"label": "r1", "model_override": "typo-model"})
            assert e.value.code == 422
            assert extra.model_override == "stub-model"
            # unknown label -> 404
            with pytest.raises(urllib.error.HTTPError) as e:
                call(server, "/internal/workers", {"label": "ghost",
                                                   "pixel_cap": 1})
            assert e.value.code == 404
        finally:
            world.workers.remove(extra)

    def test_worker_models_route(self, server):
        world = server.source
        extra = WorkerNode("rm", StubBackend(), avg_ipm=5.0)
        world.add_worker(extra)
        try:
            out = call(server, "/internal/worker-models", {"label": "rm"})
            assert out["models"] == ["stub-model"]
            with pytest.raises(urllib.error.HTTPError) as e:
                call(server, "/internal/worker-models", {"label": "ghost"})
            assert e.value.code == 404
        finally:
            world.workers.remove(extra)

    def test_worker_endpoint_edit_route(self, server):
        """In-place address/port/credential edit (reference save_worker_btn,
        ui.py:100-159) through POST /internal/workers."""
        world = server.source
        out = call(server, "/internal/workers",
                   {"action": "add", "label": "ed", "address": "h1",
                    "port": 7861, "user": "u1", "password": "p1"})
        assert out["added"] == "ed"
        try:
            w = world.get_worker("ed")
            out = call(server, "/internal/workers",
                       {"label": "ed", "address": "h2", "port": 7999,
                        "tls": True, "user": "u2"})
            assert out["updated"] == "ed"
            assert w.backend.address == "h2"
            assert w.backend.port == 7999
            assert w.backend.tls is True
            assert w.backend.user == "u2"
            assert w.backend.password == "p1"  # omitted field is kept
            # cached sync state forgotten: new endpoint = new process
            assert w.loaded_model is None and w.supported_scripts is None
            # editing the master's endpoint -> 422
            with pytest.raises(urllib.error.HTTPError) as e:
                call(server, "/internal/workers",
                     {"label": "m", "address": "h3"})
            assert e.value.code == 422
        finally:
            call(server, "/internal/workers",
                 {"action": "remove", "label": "ed"})

    def test_embeddings_route_tolerates_broken_file(self, tmp_path):
        from safetensors.numpy import save_file
        import numpy as np
        import types

        from stable_diffusion_webui_distributed_tpu.models.embeddings import (
            EmbeddingStore,
        )

        save_file({"emb_params": np.ones((2, 8), np.float32)},
                  str(tmp_path / "good.safetensors"))
        (tmp_path / "broken.safetensors").write_bytes(b"junk")
        registry = types.SimpleNamespace(
            embedding_store=EmbeddingStore(str(tmp_path)))
        srv = ApiServer(make_world(), registry=registry,
                        host="127.0.0.1", port=0)
        srv.start()
        try:
            out = call(srv, "/sdapi/v1/embeddings")
        finally:
            srv.stop()
        assert out["loaded"]["good"]["vectors"] == 2
        assert "broken" in out["skipped"]  # unloadable must not 500

    def test_workers_add_remove_routes(self, server):
        world = server.source
        out = call(server, "/internal/workers",
                   {"action": "add", "label": "new-r", "address": "h1",
                    "port": 7861})
        assert out["added"] == "new-r"
        assert world.get_worker("new-r") is not None
        try:
            # duplicate add -> 422
            with pytest.raises(urllib.error.HTTPError) as e:
                call(server, "/internal/workers",
                     {"action": "add", "label": "new-r", "address": "h1",
                      "port": 7861})
            assert e.value.code == 422
        finally:
            out = call(server, "/internal/workers",
                       {"action": "remove", "label": "new-r"})
        assert out["removed"] == "new-r"
        assert world.get_worker("new-r") is None
        with pytest.raises(urllib.error.HTTPError) as e:
            call(server, "/internal/workers",
                 {"action": "remove", "label": "new-r"})
        assert e.value.code == 404

    def test_restart_all_route(self, server):
        world = server.source
        extra = WorkerNode("r2", StubBackend(), avg_ipm=5.0)
        world.add_worker(extra)
        try:
            out = call(server, "/internal/restart-all", {})
            assert out["restarted"] == {"r2": True}
            assert extra.backend.restarted
        finally:
            world.workers.remove(extra)

    def test_benchmark_route_sweeps_fleet(self, server):
        import time

        world = server.source
        fresh = WorkerNode("r3", StubBackend())  # no calibration yet
        world.add_worker(fresh)
        try:
            out = call(server, "/internal/benchmark", {"rebenchmark": False})
            assert out["started"] is True
            deadline = time.monotonic() + 20
            while fresh.cal.avg_ipm is None and time.monotonic() < deadline:
                time.sleep(0.05)
            assert fresh.cal.avg_ipm and fresh.cal.avg_ipm > 0
        finally:
            world.workers.remove(fresh)

    def test_status_reports_settings(self, server):
        out = call(server, "/internal/status")
        s = out["settings"]
        assert {"job_timeout", "complement_production", "step_scaling",
                "thin_client_mode"} <= set(s)

    def test_options_apply_scheduler_settings(self, server):
        world = server.source
        old = world.job_timeout
        try:
            call(server, "/sdapi/v1/options",
                 {"distributed_job_timeout": 11, "step_scaling": True})
            assert world.job_timeout == 11.0
            assert world.step_scaling is True
        finally:
            world.job_timeout = old
            world.step_scaling = False

    def test_status_panel_html(self, server):
        url = f"http://127.0.0.1:{server.port}/"
        with urllib.request.urlopen(url, timeout=10) as r:
            assert "text/html" in r.headers["Content-Type"]
            body = r.read().decode()
        assert "sdtpu" in body and "/internal/status" in body
        # pin UX (VERDICT r4 items 6/7): datalist-fed pin input + the
        # unvalidated-pin warning marker wired into the worker table
        assert 'list="ew_pin_models"' in body
        assert 'datalist id="ew_pin_models"' in body
        assert "pin_validated" in body

    def test_internal_status(self, server):
        out = call(server, "/internal/status")
        assert {"model", "workers", "progress", "timings", "logs"} <= set(out)
        labels = {w["label"] for w in out["workers"]}
        assert "m" in labels

    def test_stage_timings_recorded(self, server):
        from stable_diffusion_webui_distributed_tpu.runtime import trace

        trace.STATS.record("unit-test-stage", 0.25)
        out = call(server, "/internal/status")
        assert out["timings"]["unit-test-stage"]["count"] >= 1

    def test_reset_mpe(self, server):
        w = server.source.workers[0]
        w.cal.eta_percent_error.extend([5.0, -3.0])
        out = call(server, "/internal/reset-mpe", {})
        assert out["cleared"] == ["m"]
        assert w.cal.eta_percent_error == []

    def test_profile_endpoint_validates(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            call(server, "/internal/profile", {"action": "bogus"})
        assert e.value.code == 422


class TestStylesAndGrid:
    def test_styles_applied(self, tmp_path):
        from stable_diffusion_webui_distributed_tpu.pipeline.styles import (
            apply_styles, load_styles,
        )
        from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
            GenerationPayload,
        )

        csv_path = tmp_path / "styles.csv"
        csv_path.write_text(
            "name,prompt,negative_prompt\n"
            "anime,\"{prompt}, anime style\",\"ugly\"\n"
            "suffix-only,\"best quality\",\"\"\n")
        styles = load_styles(str(csv_path))
        p = GenerationPayload(prompt="a cow", styles=["anime", "suffix-only",
                                                      "missing"])
        apply_styles(p, styles)
        assert p.prompt == "a cow, anime style, best quality"
        assert p.negative_prompt == "ugly"
        assert p.styles == []

    def test_return_grid_option(self, server):
        call(server, "/sdapi/v1/options", {"return_grid": True})
        try:
            out = call(server, "/sdapi/v1/txt2img",
                       {"prompt": "g", "batch_size": 3, "seed": 5,
                        "steps": 2, "width": 64, "height": 64})
            # stub images aren't decodable PNGs -> grid skipped gracefully
            assert len(out["images"]) == 3
        finally:
            call(server, "/sdapi/v1/options", {"return_grid": False})

    def test_make_grid(self):
        import numpy as np

        from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
            array_to_b64png, b64png_to_array,
        )
        from stable_diffusion_webui_distributed_tpu.server.api import (
            _make_grid_b64,
        )

        imgs = [array_to_b64png(np.full((8, 8, 3), i * 40, np.uint8))
                for i in range(3)]
        grid = b64png_to_array(_make_grid_b64(imgs))
        assert grid.shape == (16, 16, 3)  # 2x2 grid with one empty cell
        assert grid[0, 0, 0] == 0 and grid[0, 8, 0] == 40
        assert grid[8, 0, 0] == 80 and grid[8, 8, 0] == 0


class TestAuth:
    def test_basic_auth(self):
        srv = ApiServer(make_world(), host="127.0.0.1", port=0,
                        user="u", password="p")
        srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                call(srv, "/sdapi/v1/progress")
            assert e.value.code == 401
            import base64

            tok = base64.b64encode(b"u:p").decode()
            out = call(srv, "/sdapi/v1/progress",
                       headers={"Authorization": f"Basic {tok}"})
            assert "progress" in out
        finally:
            srv.stop()


class TestLoopClosure:
    """This framework's HTTPBackend drives this framework's server: the
    distributed deployment story (master World -> remote node) end to end."""

    def test_http_backend_roundtrip(self, server):
        backend = HTTPBackend("127.0.0.1", server.port)
        assert backend.reachable()
        payload = GenerationPayload(prompt="net cow", batch_size=4, seed=200,
                                    steps=4, width=64, height=64)
        # remote generates the sub-range [2, 4) — seed offset arithmetic
        # rides the wire exactly like the reference (distributed.py:297-305)
        result = backend.generate(payload, 2, 2)
        assert len(result.images) == 2
        assert result.seeds == [202, 203]

    def test_world_of_http_workers(self, server):
        w = World(ConfigModel())
        w.add_worker(WorkerNode(
            "remote", HTTPBackend("127.0.0.1", server.port), avg_ipm=10.0))
        r = w.execute(GenerationPayload(prompt="dist", batch_size=3,
                                        seed=300, steps=4, width=64,
                                        height=64))
        assert len(r.images) == 3
        assert r.seeds == [300, 301, 302]
        assert all("Worker Label: remote" in t for t in r.infotexts)

    def test_sampler_404_retries_with_euler_a(self):
        """A legacy remote that 404s an unknown sampler gets one retry with
        Euler a (reference worker.py:457-467)."""
        import http.server
        import threading

        seen = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: D102
                pass

            def do_POST(self):
                body = json.loads(self.rfile.read(
                    int(self.headers["Content-Length"])))
                seen.append(body["sampler_name"])
                if len(seen) == 1:
                    payload = b'{"detail": "Sampler not found"}'
                    self.send_response(404)
                else:
                    payload = json.dumps(
                        {"images": ["ok"], "info": "{}"}).encode()
                    self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            backend = HTTPBackend("127.0.0.1", httpd.server_port)
            result = backend.generate(GenerationPayload(
                prompt="x", sampler_name="Fancy New Sampler", seed=1), 0, 1)
            assert result.images == ["ok"]
            assert seen == ["Fancy New Sampler", "Euler a"]
        finally:
            httpd.shutdown()

    def test_models_and_options_via_backend(self, server):
        backend = HTTPBackend("127.0.0.1", server.port)
        assert isinstance(backend.available_models(), list)
        backend.load_options("some-model")  # no registry -> option recorded
        assert backend.memory_info()["cuda"]["system"]["free"] >= 0


class TestEndpointEditUnsupportedSource:
    def test_endpoint_fields_422_not_silently_dropped(self):
        """A source without update_worker_endpoint must reject endpoint
        edits (advisor r4: a 200 echoing unapplied fields hides the drop)."""

        class BareSource:
            workers = []

            def execute(self, payload):
                raise NotImplementedError

            def configure_worker(self, label, **kw):
                return True

        from stable_diffusion_webui_distributed_tpu.server.api import (
            ApiError, ApiServer,
        )

        srv = ApiServer(BareSource(), state=GenerationState())
        with pytest.raises(ApiError) as e:
            srv.handle_workers_post(
                {"label": "w", "address": "10.0.0.1", "port": 7860})
        assert e.value.status == 422
        assert "endpoint edits" in e.value.detail


class TestPinValidatedSurface:
    def test_worker_rows_carry_pin_validated(self, server):
        world = server.source
        n = WorkerNode("pv", StubBackend(), avg_ipm=5.0)
        n.backend.models = ["served.safetensors"]
        world.add_worker(n)
        try:
            call(server, "/internal/workers",
                 {"label": "pv", "model_override": "served.safetensors"})
            rows = call(server, "/internal/workers")
            row = next(r for r in rows if r["label"] == "pv")
            # validated live against the stub's model list
            assert row["pin_validated"] is True
        finally:
            world.workers.remove(n)

    def test_unreachable_node_pin_flagged_unvalidated(self, server):
        from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
            StubBehavior,
        )

        world = server.source
        n = WorkerNode("down", StubBackend(StubBehavior(fail_reachable=True)),
                       avg_ipm=5.0)

        def boom():
            raise ConnectionError("down")

        n.backend.available_models = boom
        world.add_worker(n)
        try:
            call(server, "/internal/workers",
                 {"label": "down", "model_override": "typo.safetensors"})
            rows = call(server, "/internal/workers")
            row = next(r for r in rows if r["label"] == "down")
            assert row["pin_validated"] is False
        finally:
            world.workers.remove(n)

"""Fleet scheduler: priority classes, WFQ gate, quotas, SLO admission,
chunk-boundary preemption, and slice autoscale decisions.

The policy layer (fleet/) is pure host code driven by injectable clocks,
so everything except the engine-resume tests runs with zero device work.
The preemption tests use the TINY pipeline and assert the tentpole
acceptance property directly: a preempted-then-resumed request is
byte-identical to an unpreempted run and triggers zero new compiles.
"""

import threading
import time

import pytest

from stable_diffusion_webui_distributed_tpu.fleet.admission import (
    AdmissionController, FleetRejected, cadence_speedup,
)
from stable_diffusion_webui_distributed_tpu.fleet.policy import (
    BATCH, BEST_EFFORT, INTERACTIVE, EnginePreemptHook, FleetGate,
    FleetPolicy, GateEntry, WeightedFairQueue, _parse_class_weights,
    fleet_enabled,
)
from stable_diffusion_webui_distributed_tpu.fleet.quotas import (
    QuotaLedger, TokenBucket,
)
from stable_diffusion_webui_distributed_tpu.fleet.slices import (
    AutoscaleEngine, SliceInfo, SliceRegistry,
)
from stable_diffusion_webui_distributed_tpu.obs import (
    prometheus as obs_prom,
)
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.scheduler.eta import (
    EtaCalibration,
)


class FakeClock:
    def __init__(self, now=0.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def payload(**kw):
    defaults = dict(prompt="a cow", steps=20, width=512, height=512,
                    seed=7, sampler_name="Euler a")
    defaults.update(kw)
    return GenerationPayload(**defaults)


# -- policy + class table ----------------------------------------------------

class TestPolicy:
    def test_parse_class_weights(self):
        assert _parse_class_weights("interactive:8, batch:2") == {
            "interactive": 8.0, "batch": 2.0}
        with pytest.raises(ValueError):
            _parse_class_weights("interactive:zero")
        with pytest.raises(ValueError):
            _parse_class_weights("interactive:-1")

    def test_resolve(self):
        pol = FleetPolicy()
        assert pol.resolve("").name == INTERACTIVE
        assert pol.resolve(None).name == INTERACTIVE
        assert pol.resolve("no-such-class").name == BEST_EFFORT
        assert pol.resolve(BATCH).preemptible
        assert not pol.resolve(INTERACTIVE).preemptible
        assert BATCH in pol.resolve(INTERACTIVE).preempts
        assert pol.resolve(INTERACTIVE).slo_s == 30.0

    def test_custom_class_scheduled_like_batch(self):
        pol = FleetPolicy(weights={"research": 4.0})
        cp = pol.resolve("research")
        assert cp.weight == 4.0 and cp.preemptible and cp.slo_s is None

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("SDTPU_FLEET_CLASSES", "interactive:16,batch:4")
        monkeypatch.setenv("SDTPU_SLO_INTERACTIVE_S", "12")
        pol = FleetPolicy.from_env()
        assert pol.resolve(INTERACTIVE).weight == 16.0
        assert pol.resolve(BATCH).weight == 4.0
        assert pol.resolve(INTERACTIVE).slo_s == 12.0

    def test_fleet_enabled_precedence(self, monkeypatch):
        monkeypatch.delenv("SDTPU_FLEET", raising=False)
        assert fleet_enabled() is False

        class Cfg:
            fleet_enabled = True

        assert fleet_enabled(Cfg()) is True
        monkeypatch.setenv("SDTPU_FLEET", "0")
        assert fleet_enabled(Cfg()) is False  # env wins over config
        monkeypatch.setenv("SDTPU_FLEET", "1")
        assert fleet_enabled() is True


# -- weighted-fair queue -----------------------------------------------------

class TestWFQ:
    def test_weight_order(self):
        clk = FakeClock()
        pol = FleetPolicy(aging_s=1e9)
        q = WeightedFairQueue(aging_s=1e9, clock=clk)
        e_best = GateEntry(pol.resolve(BEST_EFFORT), cost=1)
        e_batch = GateEntry(pol.resolve(BATCH), cost=1)
        e_int = GateEntry(pol.resolve(INTERACTIVE), cost=1)
        for e in (e_best, e_batch, e_int):  # arrival order worst-first
            q.push(e)
        order = []
        for _ in range(3):
            e = q.select()
            order.append(e.policy.name)
            q.remove(e)
        assert order == [INTERACTIVE, BATCH, BEST_EFFORT]
        assert q.select() is None

    def test_fair_share_within_class(self):
        # same class, two tenants: the second tenant's first image goes
        # ahead of the first tenant's backlog (tags accumulate per flow)
        clk = FakeClock()
        pol = FleetPolicy(aging_s=1e9)
        q = WeightedFairQueue(aging_s=1e9, clock=clk)
        a1 = GateEntry(pol.resolve(BATCH), tenant="a", cost=1)
        a2 = GateEntry(pol.resolve(BATCH), tenant="a", cost=1)
        b1 = GateEntry(pol.resolve(BATCH), tenant="b", cost=1)
        q.push(a1)
        q.push(a2)
        q.push(b1)
        order = []
        for _ in range(3):
            e = q.select()
            order.append(e)
            q.remove(e)
        assert order.index(b1) < order.index(a2)

    def test_aging_override(self):
        clk = FakeClock()
        pol = FleetPolicy(aging_s=10.0)
        q = WeightedFairQueue(aging_s=10.0, clock=clk)
        e_old = GateEntry(pol.resolve(BEST_EFFORT), cost=1)
        q.push(e_old)
        clk.advance(11.0)
        e_new = GateEntry(pol.resolve(INTERACTIVE), cost=1)
        q.push(e_new)
        # best_effort has waited past the aging bound: served first even
        # though interactive's tag is far smaller
        assert q.select() is e_old

    def test_repush_keeps_tag(self):
        clk = FakeClock()
        pol = FleetPolicy(aging_s=1e9)
        q = WeightedFairQueue(aging_s=1e9, clock=clk)
        e_batch = GateEntry(pol.resolve(BATCH), cost=4)
        q.push(e_batch)
        q.remove(e_batch)  # it ran, then got preempted
        tag = e_batch.tag
        later = GateEntry(pol.resolve(BATCH), tenant="other", cost=4)
        q.push(later)
        q.push(e_batch, recost=False)
        assert e_batch.tag == tag  # no double charge
        # the preempted runner resumes ahead of later-arrived equal work
        assert q.select() is e_batch

    def test_depth_by_class(self):
        pol = FleetPolicy()
        q = WeightedFairQueue()
        q.push(GateEntry(pol.resolve(BATCH)))
        q.push(GateEntry(pol.resolve(BATCH)))
        q.push(GateEntry(pol.resolve(INTERACTIVE)))
        assert q.depth() == 3
        assert q.depth_by_class() == {BATCH: 2, INTERACTIVE: 1}


# -- quotas ------------------------------------------------------------------

class TestQuotas:
    def test_token_bucket_refill(self):
        clk = FakeClock()
        b = TokenBucket(rate=1.0, burst=2.0, clock=clk)
        assert b.try_take(2)
        assert not b.try_take(1)
        assert b.retry_after(1) == pytest.approx(1.0)
        clk.advance(1.5)
        assert b.try_take(1)
        assert b.available() == pytest.approx(0.5)

    def test_ledger_per_tenant_isolation(self):
        clk = FakeClock()
        led = QuotaLedger(images_per_minute=60.0, burst=2.0, clock=clk)
        assert led.enabled
        assert led.admit("a", 2) is None
        retry = led.admit("a", 1)
        assert retry is not None and retry >= 1.0
        assert led.admit("b", 2) is None  # b has its own bucket
        s = led.summary()
        assert s["admitted"] == 2 and s["throttled"] == 1
        assert set(s["tenants"]) == {"a", "b"}

    def test_refund_restores_tokens(self):
        # REVIEW fix: a request rejected AFTER the quota withdrawal (SLO)
        # must not leave its tenant charged for work never performed
        clk = FakeClock()
        led = QuotaLedger(images_per_minute=60.0, burst=2.0, clock=clk)
        assert led.admit("a", 2) is None
        led.refund("a", 2)
        assert led.admit("a", 2) is None  # tokens are back, no refill used
        led.refund("a", 100)
        assert led._bucket("a").available() == pytest.approx(2.0)  # capped
        QuotaLedger(images_per_minute=0.0).refund("x", 5)  # disabled: no-op

    def test_disabled_ledger_admits_everything(self):
        led = QuotaLedger(images_per_minute=0.0)
        assert not led.enabled
        for _ in range(100):
            assert led.admit("t", 100) is None

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("SDTPU_QUOTA_IPM", "120")
        monkeypatch.setenv("SDTPU_QUOTA_BURST", "3")
        led = QuotaLedger.from_env()
        assert led.rate == pytest.approx(2.0)
        assert led.burst == 3.0


# -- admission ---------------------------------------------------------------

class TestAdmission:
    # cal at 6 ipm, benchmark defaults (20 steps, 512x512) -> a default
    # payload predicts 10s of compute; the pinned zero-MPE history keeps
    # the process-wide ETA gauge (other tests may feed it) out of the math
    def controller(self):
        return AdmissionController(
            calibration=EtaCalibration(avg_ipm=6.0,
                                       eta_percent_error=[0.0]),
            fewstep=12)

    def test_accept_when_inside_slo(self):
        pol = FleetPolicy(slo_interactive_s=15.0).resolve(INTERACTIVE)
        d = self.controller().decide(payload(), pol)
        assert d.action == "accept"
        assert d.predicted_s == pytest.approx(10.0, rel=0.01)

    def test_accept_without_calibration(self):
        pol = FleetPolicy(slo_interactive_s=1.0).resolve(INTERACTIVE)
        d = AdmissionController(calibration=None).decide(payload(), pol)
        assert d.action == "accept"
        d = AdmissionController(
            calibration=EtaCalibration()).decide(payload(), pol)
        assert d.action == "accept"

    def test_accept_without_slo(self):
        d = self.controller().decide(
            payload(), FleetPolicy().resolve(BATCH))
        assert d.action == "accept"

    def test_degrade_cadence(self):
        # 10s * speedup(2)=0.725 -> 7.25s fits an 8s SLO
        pol = FleetPolicy(slo_interactive_s=8.0).resolve(INTERACTIVE)
        d = self.controller().decide(payload(), pol)
        assert d.action == "degrade"
        assert d.overrides == {"deepcache": 2}
        assert d.steps is None
        assert d.predicted_s == pytest.approx(
            10.0 * cadence_speedup(2), rel=0.01)

    def test_degrade_fewstep(self):
        # cadence alone tops out at 10*0.633=6.33s; a 6s SLO needs the
        # few-step rung: 12 steps -> 6s compute * 0.633 = 3.8s
        pol = FleetPolicy(slo_interactive_s=6.0).resolve(INTERACTIVE)
        d = self.controller().decide(payload(), pol)
        assert d.action == "degrade"
        assert d.overrides == {"deepcache": 3}
        assert d.steps == 12

    def test_reject_when_nothing_fits(self):
        pol = FleetPolicy(slo_interactive_s=2.0).resolve(INTERACTIVE)
        d = self.controller().decide(payload(), pol)
        assert d.action == "reject"
        assert "2.0s" in d.detail

    def test_degrade_int8_rung(self):
        # the final rung before reject: fewstep tops out at 6s * 0.633 =
        # 3.8s, so a 3s SLO needs the int8 precision stacked on top
        # (prior factor 0.55): 6 * 0.633 * 0.55 = 2.09s fits
        pol = FleetPolicy(slo_interactive_s=3.0).resolve(INTERACTIVE)
        d = self.controller().decide(payload(), pol)
        assert d.action == "degrade"
        assert d.overrides == {"deepcache": 3, "precision": "int8"}
        assert d.steps == 12
        assert "int8" in d.detail
        assert d.predicted_s <= 3.0

    def test_int8_request_has_no_int8_rung(self):
        # a request already asking for int8 is predicted at int8 speed
        # (5.5s compute) but cannot degrade to int8 again: at a 2s SLO the
        # fewstep rung lands at 6*0.55*0.633 = 2.09s and it rejects
        pol = FleetPolicy(slo_interactive_s=2.0).resolve(INTERACTIVE)
        d = self.controller().decide(payload(precision="int8"), pol)
        assert d.action == "reject"

    def test_int8_samples_never_skew_bf16_calibration(self):
        # ETA isolation: a fleet-degraded int8 completion must update the
        # per-precision factor only — the bf16 MPE history and the ETA it
        # feeds stay bit-identical
        from stable_diffusion_webui_distributed_tpu.scheduler import eta

        cal = EtaCalibration(avg_ipm=6.0, eta_percent_error=[0.0])
        before = eta.predict_eta(cal, payload())
        eta.record_eta_error(cal, predicted=4.0, actual=2.0,
                             precision="int8")
        assert cal.eta_percent_error == [0.0]
        assert eta.predict_eta(cal, payload()) == before
        # the int8 factor moved from the prior toward the observed ratio
        # (0.55 * (0.7 + 0.3 * 0.5) = 0.4675) and int8 ETAs now use it
        assert cal.precision_scale["int8"] == pytest.approx(0.4675)
        assert eta.predict_eta(cal, payload(), precision="int8") == \
            pytest.approx(before * 0.4675)

    def test_queue_wait_is_never_rescaled(self):
        # 10s compute + 5s wait; an SLO of 12s can be met by cadence 2
        # only because the wait stays additive (10*0.725+5 = 12.25 > 12
        # fails; cadence 3: 10*0.633+5 = 11.3 fits)
        pol = FleetPolicy(slo_interactive_s=12.0).resolve(INTERACTIVE)
        d = self.controller().decide(payload(), pol,
                                     {"queue_wait": 5.0})
        assert d.action == "degrade"
        assert d.overrides == {"deepcache": 3}

    def test_rejected_exception_floors_retry_after(self):
        e = FleetRejected("slo", "x", retry_after=0.01)
        assert e.retry_after == 1.0
        assert e.reason == "slo"


# -- gate + preemption (host-only) -------------------------------------------

class TestGate:
    def test_acquire_release_orders_waiters(self):
        pol = FleetPolicy(aging_s=1e9, quantum_s=0.0)
        gate = FleetGate(pol)
        holder = GateEntry(pol.resolve(BATCH), cost=1)
        gate.acquire(holder)
        order = []
        done = []

        def waiter(name, cls):
            e = GateEntry(pol.resolve(cls), cost=1)
            gate.acquire(e)
            order.append(name)
            gate.release(e)
            done.append(name)

        threads = [
            threading.Thread(target=waiter, args=("best", BEST_EFFORT)),
            threading.Thread(target=waiter, args=("inter", INTERACTIVE)),
        ]
        threads[0].start()
        while gate.queue.depth() < 1:
            time.sleep(0.005)
        threads[1].start()
        while gate.queue.depth() < 2:
            time.sleep(0.005)
        gate.release(holder)
        for t in threads:
            t.join(timeout=10)
        assert order == ["inter", "best"]
        assert done == ["inter", "best"]

    def test_should_yield_only_for_entitled_waiters(self):
        pol = FleetPolicy(aging_s=1e9, quantum_s=0.0)
        gate = FleetGate(pol)
        batch = GateEntry(pol.resolve(BATCH), cost=1)
        gate.acquire(batch)
        assert not gate.should_yield(batch)  # empty queue
        # another batch job does NOT preempt a batch runner
        gate.queue.push(GateEntry(pol.resolve(BATCH), cost=1))
        assert not gate.should_yield(batch)
        gate.queue.push(GateEntry(pol.resolve(INTERACTIVE), cost=1))
        assert gate.should_yield(batch)
        # interactive runners are never asked to yield
        gate.release(batch)

    def test_quantum_suppresses_early_yield(self):
        clk = FakeClock()
        pol = FleetPolicy(aging_s=1e9, quantum_s=5.0)
        gate = FleetGate(pol, clock=clk)
        batch = GateEntry(pol.resolve(BATCH), cost=1)
        gate.acquire(batch)
        gate.queue.push(GateEntry(pol.resolve(INTERACTIVE), cost=1))
        assert not gate.should_yield(batch)  # inside the quantum
        clk.advance(6.0)
        assert gate.should_yield(batch)
        gate.release(batch)

    def test_yield_device_runs_interloper_then_resumes(self):
        obs_prom.clear_histograms()
        pol = FleetPolicy(aging_s=1e9, quantum_s=0.0)
        gate = FleetGate(pol)
        batch = GateEntry(pol.resolve(BATCH), cost=4)
        gate.acquire(batch)
        log = []

        def interactive():
            e = GateEntry(pol.resolve(INTERACTIVE), cost=1)
            gate.acquire(e)
            log.append("interactive-ran")
            gate.release(e)

        t = threading.Thread(target=interactive)
        t.start()
        while not gate.should_yield(batch):
            time.sleep(0.005)
        gate.yield_device(batch)  # blocks until interactive releases
        log.append("batch-resumed")
        t.join(timeout=10)
        gate.release(batch)
        assert log == ["interactive-ran", "batch-resumed"]
        assert gate.preemption_count() == 1
        snap = obs_prom.FLEET_COUNTERS["preemptions"].snapshot()
        assert snap == {(BATCH,): 1.0}

    def test_acquire_cleans_up_on_wait_exception(self, monkeypatch):
        # REVIEW fix: a waiter that dies inside cv.wait must remove its
        # queue entry — an orphan wins the aging branch forever and
        # deadlocks every later waiter
        pol = FleetPolicy(aging_s=1e9, quantum_s=0.0)
        gate = FleetGate(pol)
        holder = GateEntry(pol.resolve(BATCH), cost=1)
        gate.acquire(holder)

        def dying_wait(*a, **k):
            raise KeyboardInterrupt

        monkeypatch.setattr(gate._cv, "wait", dying_wait)
        with pytest.raises(KeyboardInterrupt):
            gate.acquire(GateEntry(pol.resolve(INTERACTIVE), cost=1))
        assert gate.queue.depth() == 0  # no orphan left behind
        monkeypatch.undo()
        gate.release(holder)
        nxt = GateEntry(pol.resolve(INTERACTIVE), cost=1)
        gate.acquire(nxt)  # the gate still serves later waiters
        gate.release(nxt)

    def test_hook_is_thread_filtered(self):
        pol = FleetPolicy(aging_s=1e9, quantum_s=0.0)
        gate = FleetGate(pol)
        batch = GateEntry(pol.resolve(BATCH), cost=1)
        gate.acquire(batch)
        gate.queue.push(GateEntry(pol.resolve(INTERACTIVE), cost=1))
        hook = EnginePreemptHook(gate, batch)
        assert hook.should_yield()  # owner thread
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(hook.should_yield()))
        t.start()
        t.join()
        assert seen == [False]  # interloper thread: no-op
        gate.release(batch)

    def test_summary_shape(self):
        gate = FleetGate(FleetPolicy())
        s = gate.summary()
        assert s["queue_depth"] == 0 and s["running_class"] is None
        assert s["classes"][INTERACTIVE]["weight"] == 8.0


# -- slice registry + autoscale ----------------------------------------------

class TestSlices:
    def test_registry_clamps_replicas(self):
        reg = SliceRegistry()
        reg.register(SliceInfo("s0", group="sdxl/bf16", min_replicas=1,
                               max_replicas=3))
        reg.set_replicas("s0", 99)
        assert reg.get("s0").replicas == 3
        reg.set_replicas("s0", 0)
        assert reg.get("s0").replicas == 1
        assert reg.for_group("sdxl/bf16")[0].name == "s0"

    def test_scale_up_down_with_cooldown(self):
        clk = FakeClock()
        reg = SliceRegistry()
        reg.register(SliceInfo("s0", max_replicas=2))
        p95 = [10.0]
        seen = []
        eng = AutoscaleEngine(reg, quantile_source=lambda: p95[0],
                              up_p95_s=5.0, down_p95_s=0.5,
                              cooldown_s=60.0, clock=clk)
        eng.add_hook(seen.append)

        d = eng.decide()
        assert [x.direction for x in d] == ["up"]
        assert reg.get("s0").replicas == 2
        assert eng.decide() == []  # cooldown
        clk.advance(61.0)
        assert eng.decide() == []  # at max_replicas
        p95[0] = 0.1
        clk.advance(61.0)
        d = eng.decide()
        assert [x.direction for x in d] == ["down"]
        assert reg.get("s0").replicas == 1
        assert len(seen) == 2 and len(eng.history()) == 2
        assert len(eng.summary()["decisions"]) == 2

    def test_default_signal_reads_fleet_histograms(self):
        obs_prom.clear_histograms()
        assert AutoscaleEngine(SliceRegistry(),
                               up_p95_s=5.0, down_p95_s=0.5,
                               cooldown_s=0.0).quantile_source
        obs_prom.fleet_observe_queue_wait("batch", 8.0)
        assert obs_prom.fleet_queue_wait_p95() > 5.0
        obs_prom.clear_histograms()
        assert obs_prom.fleet_queue_wait_p95() == 0.0


# -- engine preempt-resume (device work on the TINY model) -------------------

from stable_diffusion_webui_distributed_tpu.models.configs import TINY  # noqa: E402
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine  # noqa: E402
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (  # noqa: E402
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.serving.bucketer import (  # noqa: E402
    ShapeBucketer,
)
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (  # noqa: E402
    ServingDispatcher,
)
from stable_diffusion_webui_distributed_tpu.serving.metrics import METRICS  # noqa: E402
from test_pipeline import init_params  # noqa: E402


@pytest.fixture(scope="module")
def engine():
    return Engine(TINY, init_params(TINY), chunk_size=4,
                  state=GenerationState())


@pytest.fixture(scope="module")
def bucketer():
    return ShapeBucketer(shapes=[(32, 32), (48, 48)], batches=[4])


def tiny_payload(**kw):
    defaults = dict(prompt="a cow", steps=4, width=32, height=32,
                    seed=7, sampler_name="Euler a")
    defaults.update(kw)
    return GenerationPayload(**defaults)


class OneShotHook:
    """Deterministic stand-in for the fleet gate's EnginePreemptHook:
    fires at the second chunk boundary, runs a full interactive request
    re-entrantly on the same engine (exactly what a device yield does —
    the interloper executes while the batch loop's state sleeps in its
    stack frame), then never fires again."""

    def __init__(self, engine, interloper):
        self.engine = engine
        self.interloper = interloper
        self.polls = 0
        self.fired = 0
        self.result = None

    def should_yield(self):
        self.polls += 1
        return self.fired == 0 and self.polls >= 2

    def yield_device(self):
        self.fired += 1
        self.result = self.engine.generate_range(
            self.interloper, 0, None, "txt2img")


class TestEnginePreemptResume:
    def test_resume_is_byte_identical_with_zero_new_compiles(self, engine):
        batch_p = tiny_payload(steps=8, seed=70)
        inter_p = tiny_payload(steps=4, seed=71)

        # warmup: build both executables and pin the baseline bytes
        baseline = engine.generate_range(batch_p, 0, None, "txt2img")
        warm_inter = engine.generate_range(inter_p, 0, None, "txt2img")
        assert baseline.images and warm_inter.images

        METRICS.clear()
        hook = OneShotHook(engine, inter_p)
        engine.preempt_hook = hook
        try:
            preempted = engine.generate_range(batch_p, 0, None, "txt2img")
        finally:
            engine.preempt_hook = None

        assert hook.fired == 1
        # the interloper that ran INSIDE the yield is itself intact
        assert hook.result.images == warm_inter.images
        # tentpole acceptance: resumed output is byte-identical and the
        # resumed chunks reused the warmed executables (zero compiles)
        assert preempted.images == baseline.images
        assert preempted.seeds == baseline.seeds
        assert preempted.infotexts == baseline.infotexts
        assert METRICS.compile_count("chunk") == 0

    def test_resume_restores_pristine_params_after_lora_interloper(
            self, engine, monkeypatch):
        # REVIEW high fix: an interloper whose prompt carries <lora:...>
        # patches engine.params during the yield; the preempted (tagless)
        # job's remaining chunks must re-run on pristine weights
        from test_adapters import make_lora_sd
        loras = {"style": make_lora_sd(scale=2.0)}
        monkeypatch.setattr(engine, "lora_provider", loras.get)
        batch_p = tiny_payload(steps=8, seed=72)
        inter_p = tiny_payload(steps=4, seed=73,
                               prompt="a cow <lora:style:1.0>")

        baseline = engine.generate_range(batch_p, 0, None, "txt2img")
        warm_inter = engine.generate_range(inter_p, 0, None, "txt2img")
        engine.set_loras(())  # back to pristine before the preempted run

        hook = OneShotHook(engine, inter_p)
        engine.preempt_hook = hook
        try:
            preempted = engine.generate_range(batch_p, 0, None, "txt2img")
        finally:
            engine.preempt_hook = None
        assert hook.fired == 1
        assert hook.result.images == warm_inter.images  # interloper intact
        # the interloper's adapter merge did not leak into the resume
        assert preempted.images == baseline.images
        engine.set_loras(())

    def test_interloper_interrupt_does_not_truncate_resumed_job(
            self, engine):
        # REVIEW medium fix, direction 1: an interrupt raised while the
        # interloper holds the device targets the interloper — the
        # preempted job must resume with a clear latch
        batch_p = tiny_payload(steps=8, seed=74)
        inter_p = tiny_payload(steps=4, seed=75)
        baseline = engine.generate_range(batch_p, 0, None, "txt2img")

        class InterruptingHook(OneShotHook):
            def yield_device(self):
                super().yield_device()
                # the latch is still set when the yielded job reacquires
                self.engine.state.flag.interrupt()

        hook = InterruptingHook(engine, inter_p)
        engine.preempt_hook = hook
        try:
            resumed = engine.generate_range(batch_p, 0, None, "txt2img")
        finally:
            engine.preempt_hook = None
            engine.state.flag.clear()
        assert hook.fired == 1
        assert resumed.images == baseline.images  # ran to completion
        assert resumed.seeds == baseline.seeds

    def test_pre_yield_interrupt_survives_interloper(self, engine):
        # REVIEW medium fix, direction 2: an interrupt that lands between
        # the loop-top latch check and the yield must survive the
        # interloper's begin_request and stop the resumed job
        batch_p = tiny_payload(steps=8, seed=76)
        inter_p = tiny_payload(steps=4, seed=77)
        warm_inter = engine.generate_range(inter_p, 0, None, "txt2img")

        class LatchThenYieldHook(OneShotHook):
            def should_yield(self):
                fire = super().should_yield()
                if fire:
                    self.engine.state.flag.interrupt()
                return fire

            def yield_device(self):
                self.fired += 1
                # the interloper is a top-level request: its
                # begin_request clears the process-global latch
                self.engine.state.begin_request()
                self.result = self.engine.generate_range(
                    self.interloper, 0, None, "txt2img")

        hook = LatchThenYieldHook(engine, inter_p)
        engine.preempt_hook = hook
        try:
            engine.state.begin_request()
            engine.generate_range(batch_p, 0, None, "txt2img")
        finally:
            engine.preempt_hook = None
            engine.state.flag.clear()
        assert hook.fired == 1
        assert hook.result.images == warm_inter.images  # interloper intact
        # the saved latch was restored on resume: the preempted job
        # stopped at the yield boundary instead of running to completion
        assert engine.state.progress.interrupted

    def test_hook_cleared_between_requests(self, engine):
        assert engine.preempt_hook is None


# -- dispatcher integration --------------------------------------------------

class TestDispatcherFleet:
    def test_fleet_off_by_default(self, engine, bucketer, monkeypatch):
        monkeypatch.delenv("SDTPU_FLEET", raising=False)
        disp = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        assert disp.fleet is None and disp.quotas is None
        assert disp.admission is None
        assert disp.fleet_summary() is None

    def test_fleet_on_submit_and_summary(self, engine, bucketer,
                                         monkeypatch):
        monkeypatch.setenv("SDTPU_FLEET", "1")
        obs_prom.clear_histograms()
        disp = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        assert disp.fleet is not None
        r = disp.submit(tiny_payload(seed=30))
        assert len(r.images) == 1
        # class resolved (empty -> interactive) and counted per tenant
        snap = obs_prom.FLEET_COUNTERS["requests"].snapshot()
        assert snap == {("default", INTERACTIVE): 1.0}
        s = disp.fleet_summary()
        assert s["queue_depth"] == 0 and s["running_class"] is None
        assert s["quotas"]["enabled"] is False
        assert s["admission"]["calibrated"] is False

    def test_quota_throttle_raises_429_material(self, engine, bucketer,
                                                monkeypatch):
        monkeypatch.setenv("SDTPU_FLEET", "1")
        monkeypatch.setenv("SDTPU_QUOTA_IPM", "60")
        monkeypatch.setenv("SDTPU_QUOTA_BURST", "1")
        disp = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        assert disp.submit(tiny_payload(seed=31)).images
        with pytest.raises(FleetRejected) as exc:
            disp.submit(tiny_payload(seed=32))
        assert exc.value.reason == "quota"
        assert exc.value.retry_after >= 1.0
        assert disp.fleet_summary()["quotas"]["throttled"] == 1

    def test_slo_degrade_marks_result(self, engine, bucketer, monkeypatch):
        monkeypatch.setenv("SDTPU_FLEET", "1")
        METRICS.clear()  # empty wait history -> queue_wait floor = 0
        disp = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        disp.set_calibration(
            EtaCalibration(avg_ipm=6.0, eta_percent_error=[0.0]))
        # 20 steps at 32x32 predicts 10 * (32*32)/(512*512) = 0.0390625s;
        # an SLO of 0.03s fits at cadence 2 (x0.725 = 0.0283s)
        r = disp.submit(tiny_payload(steps=20, seed=33, slo_s=0.03))
        ov = r.parameters["override_settings"]
        assert ov["deepcache"] == 2
        assert "cadence 2" in ov["fleet_degraded"]
        assert len(r.images) == 1

    def test_slo_reject_feeds_no_metrics(self, engine, bucketer,
                                         monkeypatch):
        monkeypatch.setenv("SDTPU_FLEET", "1")
        METRICS.clear()
        disp = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        disp.set_calibration(
            EtaCalibration(avg_ipm=6.0, eta_percent_error=[0.0]))
        with pytest.raises(FleetRejected) as exc:
            disp.submit(tiny_payload(steps=20, seed=34, slo_s=0.001))
        assert exc.value.reason == "slo"
        # never admitted: nothing reached the request/queue-wait metrics
        s = METRICS.summary()
        assert s["requests"] == 0 and s["dispatches"] == 0
        assert METRICS.avg_queue_wait() == 0.0

    def test_slo_reject_refunds_quota(self, engine, bucketer, monkeypatch):
        # REVIEW fix: an SLO-rejected request must hand its quota tokens
        # back — a 1-token bucket still admits the next fitting request
        monkeypatch.setenv("SDTPU_FLEET", "1")
        monkeypatch.setenv("SDTPU_QUOTA_IPM", "60")
        monkeypatch.setenv("SDTPU_QUOTA_BURST", "1")
        METRICS.clear()
        disp = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        disp.set_calibration(
            EtaCalibration(avg_ipm=6.0, eta_percent_error=[0.0]))
        with pytest.raises(FleetRejected) as exc:
            disp.submit(tiny_payload(steps=20, seed=36, slo_s=0.001))
        assert exc.value.reason == "slo"
        assert disp.submit(tiny_payload(seed=37)).images

    def test_cancelled_ticket_records_no_queue_wait(self, engine, bucketer,
                                                    monkeypatch):
        # satellite fix: a cancelled-before-dispatch request must not
        # inflate the queue-wait histogram or the ETA calibration
        monkeypatch.delenv("SDTPU_FLEET", raising=False)
        disp = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        METRICS.clear()
        rid = "cancel-me"
        # batch 5 > ladder top 4 -> solo path; hold the exec lock so the
        # ticket is still queued when cancel() lands
        p = tiny_payload(batch_size=5, seed=35, request_id=rid)
        results = {}
        disp._exec_lock.acquire()
        try:
            t = threading.Thread(
                target=lambda: results.update(r=disp.submit(p)))
            t.start()
            while not disp.cancel(rid):
                time.sleep(0.005)
        finally:
            disp._exec_lock.release()
        t.join(timeout=30)
        r = results["r"]
        assert r.images == [] and r.parameters.get("cancelled") is True
        s = METRICS.summary()
        assert s["requests"] == 1  # admitted and counted...
        assert s["dispatches"] == 0  # ...but never dispatched
        assert METRICS.avg_queue_wait() == 0.0  # and no wait recorded


@pytest.mark.slow
class TestDispatcherPreemption:
    def test_preempted_batch_byte_identical_and_recompile_free(
            self, engine, bucketer, monkeypatch):
        """End-to-end tentpole run: a long preemptible batch job yields
        the device to interactive traffic at a chunk boundary and its
        output is byte-identical to an unpreempted run, with zero new
        compiles after warmup."""
        monkeypatch.setenv("SDTPU_FLEET", "1")
        monkeypatch.setenv("SDTPU_FLEET_QUANTUM_S", "0")
        disp = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        # batch 5 > ladder top -> solo preemptible run; 32 steps at
        # chunk_size 4 gives 8 yield points
        batch_p = dict(steps=32, batch_size=5, seed=40,
                       priority_class=BATCH, tenant="batch-tenant")
        inter_p = dict(steps=4, seed=41)

        baseline = disp.submit(tiny_payload(**batch_p))
        disp.submit(tiny_payload(**inter_p))  # warm the interactive shape

        METRICS.clear()
        results = {}
        t = threading.Thread(target=lambda: results.update(
            batch=disp.submit(tiny_payload(**batch_p))))
        t.start()
        deadline = time.monotonic() + 60
        while disp.fleet.summary()["running_class"] != BATCH:
            assert time.monotonic() < deadline, "batch job never started"
            time.sleep(0.002)
        results["inter"] = disp.submit(tiny_payload(**inter_p))
        t.join(timeout=120)

        assert disp.fleet.preemption_count() >= 1
        assert results["batch"].images == baseline.images
        assert results["batch"].seeds == baseline.seeds
        assert results["inter"].images  # interloper completed
        assert METRICS.compile_count("chunk") == 0

"""Tests for the deterministic schedule explorer (sim/sched.py) and its
subsystem harnesses (sim/harnesses.py).

Four layers:

- explorer mechanics: completion, task-exception capture, livelock
  detection, seeded determinism (same seed => identical trace digest)
  and schedule diversity (different seeds => different interleavings);
- injected lock-order inversion: the SAME code the static tier pins
  (tests/lint_fixtures/lockorder_bad.py, LK005 at the class line) is
  executed under the explorer and must deadlock on some seed — and the
  consistent-order fix must survive every seed;
- injected check-then-act race: the SAME code AT001 pins
  (tests/lint_fixtures/atomicity_bad.py) loses an update on some seed,
  while the sanctioned re-validate fix holds on all of them;
- the four real-subsystem harnesses (FleetGate, dispatcher coalesce +
  cancel, notifier drain + stop, StoppableDaemon stop/restart): >= 64
  seeds each, no deadlock, no livelock, invariants preserved.
"""

import importlib.util
import os
import threading

import pytest

from stable_diffusion_webui_distributed_tpu.runtime import locksan
from stable_diffusion_webui_distributed_tpu.sim import harnesses, sched

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")
SEEDS = range(64)


@pytest.fixture
def sanitized():
    """Install the lock sanitizer for one test (the explorer refuses to
    run without it), restoring prior state after."""
    was = locksan.installed()
    locksan.install()
    locksan.reset()
    yield
    locksan.reset()
    if not was:
        locksan.uninstall()


def _load_fixture(name):
    """Import a lint fixture for EXECUTION (the lint suite only parses
    them; here the same file is run under the explorer)."""
    path = os.path.join(FIXTURES, name + ".py")
    spec = importlib.util.spec_from_file_location(f"sched_fx_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fixture_findings(name):
    from stable_diffusion_webui_distributed_tpu.analysis import (
        analyze_modules,
    )
    from stable_diffusion_webui_distributed_tpu.analysis.core import (
        load_module,
    )
    path = os.path.join(FIXTURES, name + ".py")
    return analyze_modules([load_module(path, name + ".py")])


class TestExplorerMechanics:
    def test_two_racing_tasks_complete(self, sanitized):
        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

        def build(ex):
            c = Counter()
            ex.spawn(c.bump, "t1")
            ex.spawn(c.bump, "t2")
            return lambda: [] if c.n == 2 else [f"lost update: n={c.n}"]

        results = sched.explore(build, SEEDS)
        assert all(r.ok for r in results)
        assert all(r.steps > 0 for r in results)

    def test_task_exception_is_recorded_not_raised(self, sanitized):
        def build(ex):
            def boom():
                raise ValueError("injected")
            ex.spawn(boom, "boom")
            return None

        (res,) = sched.explore(build, range(1))
        assert res.completed and not res.ok
        assert "ValueError" in res.errors[0]

    def test_livelock_detection_bounds_a_spinner(self, sanitized):
        ex = sched.Explorer(seed=0, max_steps=50)
        lock = threading.Lock()

        def spin():
            while True:
                with lock:
                    pass

        ex.spawn(spin, "spinner")
        res = ex.run()
        assert res.livelock and not res.ok
        assert res.steps == 50

    def test_same_seed_is_bit_identical(self, sanitized):
        for seed in range(8):
            a = harnesses.run_harness("fleet_gate", range(seed, seed + 1))
            b = harnesses.run_harness("fleet_gate", range(seed, seed + 1))
            assert a[0].trace == b[0].trace
            assert a[0].digest() == b[0].digest()

    def test_seeds_explore_distinct_interleavings(self, sanitized):
        digests = {r.digest()
                   for r in harnesses.run_harness("fleet_gate", SEEDS)}
        assert len(digests) > 1


class TestInjectedLockOrderInversion:
    """The AB/BA deadlock, statically pinned AND dynamically reproduced
    from one fixture file."""

    def test_static_lk005_pins_the_cycle_line(self):
        findings = _fixture_findings("lockorder_bad")
        assert ("LK005", 13) in {(f.rule, f.line) for f in findings}

    def test_explorer_reproduces_the_deadlock(self, sanitized):
        fx = _load_fixture("lockorder_bad")

        def build(ex):
            pair = fx.Pair()
            ex.spawn(pair.forward, "forward")
            ex.spawn(pair.backward, "backward")
            return None

        results = sched.explore(build, SEEDS)
        dead = [r for r in results if r.deadlocked]
        assert dead, "no seed interleaved the AB/BA inversion fatally"
        # the report names both locks and who holds what
        assert "Pair.a" in dead[0].deadlock
        assert "Pair.b" in dead[0].deadlock
        for r in results:
            assert r.deadlocked or r.ok

    def test_consistent_order_survives_every_seed(self, sanitized):
        fx = _load_fixture("lockorder_bad")

        def build(ex):
            pair = fx.Pair()
            ex.spawn(pair.forward, "t1")
            ex.spawn(pair.forward, "t2")  # same order: no cycle
            return None

        assert all(r.ok for r in sched.explore(build, SEEDS))


class TestInjectedCheckThenAct:
    """The stale-read lost update, statically pinned AND dynamically
    reproduced from one fixture file."""

    def test_static_at001_pins_the_race_line(self):
        findings = _fixture_findings("atomicity_bad")
        assert ("AT001", 24) in {(f.rule, f.line) for f in findings}

    def test_explorer_breaches_the_invariant(self, sanitized):
        fx = _load_fixture("atomicity_bad")

        def build(ex):
            q = fx.Quota()
            q._balance["t"] = 2
            ex.spawn(lambda: q.reserve_value("t", 1), "r1")
            ex.spawn(lambda: q.reserve_value("t", 1), "r2")
            return lambda: [] if q._balance["t"] == 0 else [
                f"lost update: balance {q._balance['t']} != 0"]

        results = sched.explore(build, SEEDS)
        breached = [r for r in results if r.errors]
        assert breached, "no seed interleaved the check-then-act fatally"
        assert "lost update" in breached[0].errors[0]
        assert not any(r.deadlocked or r.livelock for r in results)

    def test_revalidated_fix_holds_every_seed(self, sanitized):
        fx = _load_fixture("atomicity_bad")

        def build(ex):
            q = fx.Quota()
            q._balance["t"] = 2
            ex.spawn(lambda: q.reserve_ok("t", 1), "r1")
            ex.spawn(lambda: q.reserve_ok("t", 1), "r2")
            return lambda: [] if q._balance["t"] == 0 else [
                f"lost update: balance {q._balance['t']} != 0"]

        assert all(r.ok for r in sched.explore(build, SEEDS))


class TestSubsystemHarnesses:
    @pytest.mark.parametrize("name", sorted(harnesses.HARNESSES))
    def test_64_seeds_no_deadlock_no_invariant_breach(self, sanitized,
                                                      name):
        results = harnesses.run_harness(name, SEEDS)
        bad = [r for r in results if not r.ok]
        detail = "; ".join(
            f"seed {r.seed}: deadlock={r.deadlock!r} "
            f"livelock={r.livelock} errors={r.errors}" for r in bad[:3])
        assert not bad, f"{name}: {len(bad)}/{len(results)} seeds failed: " \
                        f"{detail}"
        # the sweep must actually explore, not replay one schedule
        assert len({r.digest() for r in results}) > 1

    @pytest.mark.parametrize("name", sorted(harnesses.HARNESSES))
    def test_determinism_per_harness(self, sanitized, name):
        a = harnesses.run_harness(name, range(5, 10))
        b = harnesses.run_harness(name, range(5, 10))
        assert [r.digest() for r in a] == [r.digest() for r in b]

"""Tests for the chip-window tooling: the relay triage (the round-5
diagnosis layer bench.py's rc=3 reporting depends on) and the sweep's
wedge contract. All socket behavior is synthesized locally — no TPU, no
relay, no jax."""

import json
import socket
import subprocess
import sys
import threading

import pytest

sys.path.insert(0, "tools")

import tpu_claim_probe  # noqa: E402  (tools/ on path)


class _FakeRelay:
    """A localhost listener with pluggable accept behavior."""

    def __init__(self, mode):
        self.mode = mode            # "dead" = accept+close, "alive" = hold
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._held = []
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        self.sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            if self.mode == "dead":
                conn.close()        # instant EOF — the round-5 wedge
            else:
                self._held.append(conn)  # hold open like a live server

    def close(self):
        self._stop.set()
        self.thread.join(timeout=2)
        for c in self._held:
            c.close()
        self.sock.close()


@pytest.fixture
def patch_ports(monkeypatch):
    def _patch(port):
        monkeypatch.setattr(tpu_claim_probe, "RELAY_PORTS", (port,))
    return _patch


class TestTriage:
    def test_relay_dead_detected(self, patch_ports):
        relay = _FakeRelay("dead")
        try:
            patch_ports(relay.port)
            out = tpu_claim_probe.triage_relay(peek_s=1.0)
            entry = out[relay.port]
            assert entry["connect"] is True
            assert entry["instant_eof"] is True
            res = tpu_claim_probe.diagnose(triage_only=True)
            assert res["verdict"] == "relay-dead"
        finally:
            relay.close()

    def test_relay_alive_holds_connection(self, patch_ports):
        relay = _FakeRelay("alive")
        try:
            patch_ports(relay.port)
            out = tpu_claim_probe.triage_relay(peek_s=0.5)
            entry = out[relay.port]
            assert entry["connect"] is True
            assert entry["instant_eof"] is False
            res = tpu_claim_probe.diagnose(triage_only=True)
            assert res["verdict"] == "relay-alive-unprobed"
        finally:
            relay.close()

    def test_relay_down_detected(self, patch_ports):
        # grab a port, then close it so nothing is listening
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        patch_ports(port)
        res = tpu_claim_probe.diagnose(triage_only=True)
        assert res["verdict"] == "relay-down"

    def test_cli_exit_codes(self):
        """SDTPU_PROBE_PORTS points the REAL CLI at the synthetic dead
        relay: the rc=7 relay-dead path is pinned end-to-end."""
        relay = _FakeRelay("dead")
        try:
            proc = subprocess.run(
                [sys.executable, "tools/tpu_claim_probe.py", "--triage-only",
                 "--json"],
                capture_output=True, text=True,
                env={"PATH": "/usr/bin:/bin",
                     "SDTPU_PROBE_PORTS": str(relay.port)})
        finally:
            relay.close()
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["verdict"] == "relay-dead", (out, proc.stderr)
        assert proc.returncode == 7
        assert out["relay"][str(relay.port)]["instant_eof"] is True


class TestSweepWedgeContract:
    def test_is_wedge_classification(self):
        sys.path.insert(0, "tools")
        import sweep

        assert sweep._is_wedge({}, 3) is True            # init watchdog
        assert sweep._is_wedge(
            {"error": "ConnectionError: Connection refused"}, 1) is True
        assert sweep._is_wedge({"error": "relay wedged mid-claim"}, 1) is True
        assert sweep._is_wedge({"error": "assert 2 == 3"}, 1) is False
        assert sweep._is_wedge({"value": 27.0}, 0) is False

    def test_cells_unpack(self):
        import sweep

        for name, cell in sweep.CELLS.items():
            cfg_n, pol_kwargs, chunk, *rest = cell
            assert 1 <= cfg_n <= 5, name
            assert isinstance(pol_kwargs, dict), name
            assert chunk > 0, name
            if rest:
                assert isinstance(rest[0], dict), name


@pytest.mark.slow
class TestSweepRehearsal:
    """End-to-end rehearsal of the sweep machinery on CPU tiny mode: the
    subprocess choreography, SWEEP_ROW parsing, and jsonl append are the
    exact code path a chip window runs — validated here instead of being
    first exercised on scarce silicon."""

    def test_one_cell_tiny(self, tmp_path):
        import os

        out_file = tmp_path / "sweep_out.jsonl"
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(JAX_PLATFORMS="cpu", SDTPU_BENCH_TINY="1",
                   SDTPU_SWEEP_OUT=str(out_file),
                   SDTPU_SWEEP_DEADLINE="3000")
        proc = subprocess.run(
            [sys.executable, "tools/sweep.py", "c1-bf16"],
            capture_output=True, text=True, env=env, timeout=900)
        assert proc.returncode == 0, proc.stderr[-2000:]
        rows = [json.loads(l) for l in out_file.read_text().splitlines()]
        assert len(rows) == 1
        row = rows[0]
        assert row["cell"] == "c1-bf16"
        assert row.get("value"), row      # a real ipm number came through
        assert row["unit"] == "images/min"
        assert "wall_s" in row


@pytest.mark.slow
class TestChipSessionTraceRehearsal:
    """chip_session's profiler-trace phase, rehearsed on CPU tiny mode:
    produces PERF_TRACE_C2.md with the per-stage table and a TensorBoard
    trace dir — the exact artifact the north-star breakdown needs."""

    def test_trace_phase_tiny(self, tmp_path):
        import os

        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(JAX_PLATFORMS="cpu", SDTPU_BENCH_TINY="1",
                   SDTPU_REPO=os.getcwd(),
                   SDTPU_TRACE_OUT=str(tmp_path))  # never touch the repo
        import chip_session

        proc = subprocess.run(
            [sys.executable, "-c", chip_session._TRACE_CHILD], env=env,
            capture_output=True, text=True, timeout=600)
        assert "TRACE_OK" in proc.stdout, (proc.stdout[-500:],
                                           proc.stderr[-1500:])
        md = (tmp_path / "PERF_TRACE_C2_TINY.md").read_text()
        assert "| stage |" in md
        assert "img/s/chip" in md
        # tiny artifacts self-identify so they can never masquerade as
        # silicon evidence
        assert "TINY LOGIC-CHECK" in md and "NOT a perf claim" in md
        assert (tmp_path / "traces" / "c2-tiny").is_dir()
        # and no tiny artifact leaked into the repo (the real
        # PERF_TRACE_C2.md may legitimately exist after a chip window)
        assert not os.path.exists("PERF_TRACE_C2_TINY.md")
        assert not os.path.isdir(os.path.join("traces", "c2-tiny"))


class TestFlopsReport:
    """tools/flops_report.py: the static step-cache pricing grid. The
    XLA cost-analysis pricing itself is exercised by the (slow)
    test_stepcache FLOPs-metrics test; here the accountant is stubbed so
    the schedule arithmetic and report shape stay tier-1 fast."""

    @pytest.fixture()
    def report(self, monkeypatch):
        import types

        import flops_report
        from stable_diffusion_webui_distributed_tpu.models import (
            configs as C,
        )
        from stable_diffusion_webui_distributed_tpu.pipeline import (
            stepcache,
        )
        from stable_diffusion_webui_distributed_tpu.samplers import (
            schedules as sched,
        )

        fake_engine = types.SimpleNamespace(
            family=C.TINY, schedule=sched.sd_schedule())
        monkeypatch.setattr(flops_report, "_engine",
                            lambda family: fake_engine)

        real_request_flops = stepcache.FlopsAccountant.request_flops

        class StubAccountant:
            # rows-proportional pricing: reuse and deep each cost a
            # fraction of the full forward (reuse + deep ~= full)
            def __init__(self, engine):
                pass

            def eval_flops(self, rows, lat_h, lat_w, ctx_len, mode,
                           precision=""):
                scale = {None: 1.0, "reuse": 0.45, "deep": 0.55}[mode]
                return rows * lat_h * lat_w * scale * 1e6

            request_flops = real_request_flops

        monkeypatch.setattr(flops_report.stepcache, "FlopsAccountant",
                            StubAccountant)
        return flops_report.build_report(steps=8, families=(C.TINY,))

    def test_cut_ordering(self, report):
        cells = report["families"][0]["settings"]
        assert cells["off"]["cut_pct"] == 0.0
        cuts = [cells[k]["cut_pct"]
                for k in ("cadence2", "cadence3", "cadence3+cutoff")]
        assert all(c > 0 for c in cuts)
        assert cuts == sorted(cuts)  # each lever cuts strictly deeper

    def test_schedule_counts_cover_all_steps(self, report):
        for label, cell in report["families"][0]["settings"].items():
            sched_counts = cell["schedule"]
            reuse_or_full = (sched_counts["full_evals"]
                             + sched_counts["reuse_full_evals"]
                             + sched_counts["reuse_trunc_evals"])
            assert reuse_or_full == 8, label  # Euler: 1 eval per step

    def test_report_is_json_serializable(self, report):
        assert json.loads(json.dumps(report)) == report


class TestTraceReport:
    """tools/trace_report.py: span-tree rendering and the slowest-span
    roll-up over the /internal/trace.json artifact shape."""

    @staticmethod
    def _event(name, rid, span_id, parent_id=None, ts=0.0, dur_us=1000.0,
               **attrs):
        args = {"request_id": rid, "span_id": span_id, **attrs}
        if parent_id is not None:
            args["parent_id"] = parent_id
        return {"ph": "X", "cat": "sdtpu", "name": name, "pid": 1, "tid": 2,
                "ts": ts, "dur": dur_us, "args": args}

    @pytest.fixture()
    def trace(self):
        e = self._event
        return {"traceEvents": [
            # request A: root(1) > dispatch(2) > denoise_chunk(3)
            e("txt2img", "aaa", 1, ts=0.0, dur_us=50_000.0),
            e("dispatch.device", "aaa", 2, parent_id=1, ts=5_000.0,
              dur_us=40_000.0),
            e("denoise_chunk", "aaa", 3, parent_id=2, ts=6_000.0,
              dur_us=30_000.0),
            # request B: follower with a mirrored leader span
            e("txt2img", "bbb", 4, ts=1_000.0, dur_us=48_000.0),
            e("coalesced.dispatch", "bbb", 5, parent_id=4, ts=5_000.0,
              dur_us=40_000.0, leader_request_id="aaa"),
        ], "displayTimeUnit": "ms"}

    def test_tree_structure_and_grouping(self, trace):
        import trace_report

        report = trace_report.build_report(trace)
        assert report["event_count"] == 5
        assert list(report["requests"]) == ["aaa", "bbb"]
        tree_a = report["requests"]["aaa"]
        assert len(tree_a) == 3
        assert tree_a[0].lstrip().startswith("txt2img")
        # nesting depth shows in indentation: root < child < grandchild
        indents = [len(l) - len(l.lstrip()) for l in tree_a]
        assert indents[0] < indents[1] < indents[2]
        # the mirrored leader link survives into the rendered line
        assert any("leader_request_id=aaa" in l
                   for l in report["requests"]["bbb"])

    def test_top_stages_ranked_by_total(self, trace):
        import trace_report

        rows = trace_report.top_stages(trace_report.load_events(trace), k=2)
        assert len(rows) == 2
        assert rows[0]["name"] == "txt2img"          # 50+48 ms total
        assert rows[0]["count"] == 2
        assert rows[0]["total_ms"] >= rows[1]["total_ms"]

    def test_flightrec_shape_accepted(self, trace):
        import trace_report

        dump = {"entries": [
            {"request_id": "aaa", "reason": "error",
             "spans": trace["traceEvents"][:3]}], "capacity": 16, "count": 1}
        assert len(trace_report.load_events(dump)) == 3

    def test_request_filter(self, trace):
        import trace_report

        report = trace_report.build_report(trace, request_id="bb")
        assert list(report["requests"]) == ["bbb"]
        assert report["event_count"] == 5  # top table still whole-file

    def test_main_exit_codes(self, tmp_path, trace, capsys):
        import trace_report

        p = tmp_path / "trace.json"
        p.write_text(json.dumps(trace))
        assert trace_report.main([str(p)]) == 0
        out = capsys.readouterr().out
        assert "request aaa" in out and "top" in out

        empty = tmp_path / "empty.json"
        empty.write_text(json.dumps({"traceEvents": []}))
        assert trace_report.main([str(empty)]) == 1
        assert trace_report.main([str(tmp_path / "missing.json")]) == 2


class TestFleetReport:
    """tools/fleet_report.py: the BENCH_fleet.json digest — per-class
    rows, the FIFO-vs-fleet p95 delta, and the exit-code contract."""

    @staticmethod
    def _doc(**over):
        doc = {
            "metric": "tiny_fleet_interactive_p95_s",
            "device": "cpu",
            "classes": {
                "interactive": {"requests": 6, "completed": 6,
                                "throttled": 0, "rejected": 0,
                                "p50_s": 2.0, "p95_s": 4.0,
                                "slo_s": 10.0, "slo_attainment": 1.0},
                "batch": {"requests": 3, "completed": 3, "throttled": 0,
                          "rejected": 0, "p50_s": 20.0, "p95_s": 30.0},
                "best_effort": {"requests": 10, "completed": 8,
                                "throttled": 2, "rejected": 0,
                                "p50_s": 12.0, "p95_s": 16.0},
            },
            "baseline_fifo": {
                "interactive": {"p95_s": 16.0, "slo_attainment": 0.5},
                "batch": {"p95_s": 24.0},
                "best_effort": {"p95_s": 20.0},
            },
            "preemptions": 2,
            "quota_throttle_rate": 0.105,
            "queue_wait_p95_s": 12.5,
            "errors": [],
        }
        doc.update(over)
        return doc

    def test_summary_rows_and_delta(self):
        import fleet_report

        s = fleet_report.build_summary(self._doc())
        by_cls = {r["class"]: r for r in s["rows"]}
        assert list(by_cls) == ["interactive", "batch", "best_effort"]
        # fleet p95 4.0 vs FIFO 16.0: a 75% cut, signed negative
        assert by_cls["interactive"]["p95_delta_pct"] == -75.0
        # batch pays for the interactive win: positive delta
        assert by_cls["batch"]["p95_delta_pct"] == 25.0
        assert s["completed"] == 17
        assert s["slo_attainment"] == 1.0
        assert s["fifo_slo_attainment"] == 0.5
        assert s["preemptions"] == 2

    def test_missing_baseline_renders_dashes(self):
        import fleet_report

        s = fleet_report.build_summary(self._doc(baseline_fifo={}))
        assert all(r["p95_delta_pct"] is None for r in s["rows"])
        text = fleet_report.render(s)
        assert "interactive" in text and "-" in text

    def test_main_exit_codes(self, tmp_path, capsys):
        import fleet_report

        p = tmp_path / "BENCH_fleet.json"
        p.write_text(json.dumps(self._doc()))
        assert fleet_report.main([str(p)]) == 0
        out = capsys.readouterr().out
        assert "interactive SLO" in out and "preemptions: 2" in out

        assert fleet_report.main([str(p), "--json"]) == 0
        digest = json.loads(capsys.readouterr().out)
        assert digest["completed"] == 17

        dead = self._doc()
        for cls in dead["classes"].values():
            cls["completed"] = 0
        (tmp_path / "dead.json").write_text(json.dumps(dead))
        assert fleet_report.main([str(tmp_path / "dead.json")]) == 1

        (tmp_path / "garbage.json").write_text("{not json")
        assert fleet_report.main([str(tmp_path / "garbage.json")]) == 2
        assert fleet_report.main([str(tmp_path / "missing.json")]) == 2


class TestInt8Report:
    """tools/int8_report.py: the BENCH_int8.json digest — per-cell floor
    verdicts and the exit-code contract (1 = floors broken)."""

    @staticmethod
    def _doc(**over):
        doc = {
            "metric": "tiny_int8_min_psnr_db",
            "device": "cpu",
            "steps": 8,
            "psnr_floor_db": 20.0,
            "ssim_floor": 0.6,
            "mxu_peak_ratio_int8_vs_bf16": 2.0,
            "cells": [
                {"cell": "c1-bf16", "precision": "bf16", "cadence": 1,
                 "unet_flops_per_image": 3.78e9, "chunk_executables": 1},
                {"cell": "c1-int8", "precision": "int8", "cadence": 1,
                 "unet_flops_per_image": 3.87e9, "chunk_executables": 1,
                 "psnr_db_vs_bf16": 34.5, "ssim_vs_bf16": 0.997},
                {"cell": "c3-int8+conv", "precision": "int8+conv",
                 "cadence": 3, "unet_flops_per_image": 2.35e9,
                 "chunk_executables": 1,
                 "psnr_db_vs_bf16": 28.5, "ssim_vs_bf16": 0.985},
            ],
        }
        doc.update(over)
        return doc

    def test_summary_floor_verdicts(self):
        import int8_report

        s = int8_report.build_summary(self._doc())
        by_cell = {r["cell"]: r for r in s["rows"]}
        assert by_cell["c1-bf16"]["floors_ok"] is None  # control row
        assert by_cell["c1-int8"]["floors_ok"] is True
        assert s["quantized_cells"] == 2
        assert s["min_psnr_db"] == 28.5
        assert s["min_ssim"] == 0.985
        assert s["floors_ok"] is True

    def test_broken_floor_flips_verdict(self):
        import int8_report

        doc = self._doc()
        doc["cells"][2]["psnr_db_vs_bf16"] = 12.0
        s = int8_report.build_summary(doc)
        assert s["floors_ok"] is False
        assert "BROKEN" in int8_report.render(s)

    def test_main_exit_codes(self, tmp_path, capsys):
        import int8_report

        p = tmp_path / "BENCH_int8.json"
        p.write_text(json.dumps(self._doc()))
        assert int8_report.main([str(p)]) == 0
        out = capsys.readouterr().out
        assert "floors" in out and "HOLD" in out

        assert int8_report.main([str(p), "--json"]) == 0
        digest = json.loads(capsys.readouterr().out)
        assert digest["min_psnr_db"] == 28.5

        broken = self._doc()
        broken["cells"][1]["ssim_vs_bf16"] = 0.1
        (tmp_path / "broken.json").write_text(json.dumps(broken))
        assert int8_report.main([str(tmp_path / "broken.json")]) == 1

        empty = self._doc(cells=[])
        (tmp_path / "empty.json").write_text(json.dumps(empty))
        assert int8_report.main([str(tmp_path / "empty.json")]) == 1

        (tmp_path / "garbage.json").write_text("{not json")
        assert int8_report.main([str(tmp_path / "garbage.json")]) == 2
        assert int8_report.main([str(tmp_path / "missing.json")]) == 2


class TestClassifyTriage:
    def test_rules(self):
        c = tpu_claim_probe.classify_triage
        assert c({}) == "relay-down"
        assert c({2024: {"connect": False}}) == "relay-down"
        assert c({2024: {"connect": True, "instant_eof": True}}) == \
            "relay-dead"
        assert c({2024: {"connect": True, "instant_eof": False}}) == "alive"
        # mixed ports: ANY live port means not dead
        assert c({1: {"connect": True, "instant_eof": True},
                  2: {"connect": True, "instant_eof": False}}) == "alive"
        assert c({1: {"connect": False},
                  2: {"connect": True, "instant_eof": True}}) == "relay-dead"


class TestBenchJson:
    """tools/benchjson.py: the shared bench-artifact I/O contract every
    report CLI loads through."""

    def test_load_bench_roundtrip(self, tmp_path):
        import benchjson

        p = tmp_path / "BENCH_x.json"
        p.write_text(json.dumps({"metric": "m", "value": 1.5}))
        assert benchjson.load_bench(str(p), "t")["value"] == 1.5

    def test_load_bench_errors_are_operator_ready(self, tmp_path):
        import benchjson

        with pytest.raises(benchjson.BenchJsonError) as e:
            benchjson.load_bench(str(tmp_path / "nope.json"), "mytool",
                                 hint="python bench.py --fleet")
        assert "mytool:" in str(e.value)
        assert "python bench.py --fleet" in str(e.value)

        garbage = tmp_path / "g.json"
        garbage.write_text("{not json")
        with pytest.raises(benchjson.BenchJsonError):
            benchjson.load_bench(str(garbage), "t")

        arr = tmp_path / "a.json"
        arr.write_text("[1, 2]")
        with pytest.raises(benchjson.BenchJsonError) as e:
            benchjson.load_bench(str(arr), "t")
        assert "not a JSON object" in str(e.value)

    def test_load_ledger_skips_blanks_keeps_order(self, tmp_path):
        import benchjson

        p = tmp_path / "L.jsonl"
        p.write_text('{"kind": "serving"}\n\n{"kind": "fleet"}\n')
        rows = benchjson.load_ledger(str(p), "t")
        assert [r["kind"] for r in rows] == ["serving", "fleet"]

    def test_load_ledger_errors(self, tmp_path):
        import benchjson

        with pytest.raises(benchjson.BenchJsonError):
            benchjson.load_ledger(str(tmp_path / "nope.jsonl"), "t")
        empty = tmp_path / "e.jsonl"
        empty.write_text("\n\n")
        with pytest.raises(benchjson.BenchJsonError) as e:
            benchjson.load_ledger(str(empty), "t")
        assert "no ledger rows" in str(e.value)
        bad = tmp_path / "b.jsonl"
        bad.write_text('{"ok": 1}\n[1]\n')
        with pytest.raises(benchjson.BenchJsonError) as e:
            benchjson.load_ledger(str(bad), "t")
        assert "line 2" in str(e.value)

    def test_fmt_placeholder_and_precision(self):
        import benchjson

        assert benchjson.fmt(None) == "-"
        assert benchjson.fmt(0.5) == "0.500"
        assert benchjson.fmt(3) == "3"
        assert benchjson.fmt(2.0, suffix="x") == "2.000x"

    def test_write_json_file_and_stdout(self, tmp_path, capsys):
        import benchjson

        out = tmp_path / "r.json"
        benchjson.write_json({"a": 1}, str(out))
        assert json.loads(out.read_text()) == {"a": 1}
        assert out.read_text().endswith("\n")
        benchjson.write_json({"b": 2})
        assert json.loads(capsys.readouterr().out) == {"b": 2}


class TestBenchCompare:
    """tools/bench_compare.py: the regression gate over ledger rows and
    BENCH artifacts — exit 0 clean, 1 regressed, 2 unusable input."""

    @staticmethod
    def _row(kind="serving", **metrics):
        base = {"chunk_compiles": 2, "coalesce_factor": 4.0,
                "bucket_hit_rate": 0.5, "avg_padding_ratio": 1.19,
                "unet_flops_per_image": 1.0e10}
        base.update(metrics)
        return {"schema": 1, "kind": kind, "device": "cpu", "tiny": True,
                "metrics": base}

    def test_identical_rows_are_clean(self):
        import bench_compare

        v = bench_compare.compare(self._row(), self._row())
        assert v["ok"] is True and v["regressions"] == []
        assert v["compared"] == 5

    def test_compile_count_regression_has_zero_tolerance(self):
        import bench_compare

        v = bench_compare.compare(self._row(),
                                  self._row(chunk_compiles=3))
        assert v["ok"] is False
        assert v["regressions"] == ["chunk_compiles"]

    def test_relative_threshold_allows_noise(self):
        import bench_compare

        # coalesce_factor tolerance is 10% relative: a 5% dip is noise,
        # a 25% dip is a regression
        ok = bench_compare.compare(self._row(),
                                   self._row(coalesce_factor=3.8))
        assert ok["ok"] is True
        bad = bench_compare.compare(self._row(),
                                    self._row(coalesce_factor=3.0))
        assert bad["regressions"] == ["coalesce_factor"]

    def test_improvements_never_fail(self):
        import bench_compare

        v = bench_compare.compare(
            self._row(),
            self._row(chunk_compiles=1, coalesce_factor=8.0,
                      avg_padding_ratio=1.0, bucket_hit_rate=1.0,
                      unet_flops_per_image=5.0e9))
        assert v["ok"] is True

    def test_value_alias_maps_bench_headline(self):
        import bench_compare

        base = {"metric": "tiny_serving_coalesce_factor", "value": 4.0}
        head = {"metric": "tiny_serving_coalesce_factor", "value": 1.0}
        v = bench_compare.compare(base, head)
        assert v["regressions"] == ["coalesce_factor"]

    def test_ledger_mode_oldest_vs_newest(self, tmp_path):
        import bench_compare

        p = tmp_path / "L.jsonl"
        rows = [self._row(), {"schema": 1, "kind": "fleet",
                              "metrics": {"slo_attainment": 1.0}},
                self._row(coalesce_factor=4.2)]
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        assert bench_compare.main([str(p), "--kind", "serving"]) == 0

        rows.append(self._row(chunk_compiles=4))    # seeded regression
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        assert bench_compare.main([str(p), "--kind", "serving"]) == 1

    def test_unusable_input_exits_2(self, tmp_path, capsys):
        import bench_compare

        assert bench_compare.main([str(tmp_path / "nope.jsonl")]) == 2
        one = tmp_path / "one.jsonl"
        one.write_text(json.dumps(self._row()) + "\n")
        assert bench_compare.main([str(one)]) == 2       # need 2 rows
        assert bench_compare.main([str(one), "--base-row", "5"]) == 2

        # artifact mode: nothing watched on either side
        a = tmp_path / "a.json"
        a.write_text(json.dumps({"foo": 1}))
        assert bench_compare.main([str(a), str(a)]) == 2
        assert "nothing" in capsys.readouterr().err

    def test_json_verdict_and_current_artifacts(self, capsys):
        import bench_compare

        # the committed BENCH files must compare clean against themselves
        # (wrapper artifacts unwrap through "parsed")
        for name in ("BENCH_serving.json", "BENCH_fleet.json"):
            assert bench_compare.main([name, name, "--json"]) == 0
            v = json.loads(capsys.readouterr().out)
            assert v["ok"] is True and v["compared"] >= 2


class TestLintReport:
    """tools/lint_report.py: the JSON roll-up plus the SARIF 2.1.0 log
    code-scanning endpoints ingest. Scoped to one fixture file so the
    test stays fast; the full-package run is TestRepoGate's job."""

    FIXTURE = ["tests/lint_fixtures/env_bad.py"]

    def _report(self):
        import lint_report

        return lint_report.build_report(paths=self.FIXTURE,
                                        use_allowlist=False)

    def test_report_carries_wall_time_and_counts(self):
        rep = self._report()
        assert isinstance(rep["wall_time_s"], float)
        assert rep["wall_time_s"] >= 0.0
        assert rep["finding_count"] == 2
        assert rep["counts_by_rule"] == {"EV001": 2}

    def test_sarif_log_shape(self):
        import lint_report

        rep = self._report()
        log = lint_report.to_sarif(rep)
        assert log["version"] == "2.1.0"
        assert log["$schema"].endswith("sarif-schema-2.1.0.json")
        assert len(log["runs"]) == 1
        run = log["runs"][0]
        driver = run["tool"]["driver"]
        assert driver["name"] == "sdtpu-lint"
        rule_ids = {r["id"] for r in driver["rules"]}
        assert rule_ids == set(rep["rules"])
        for r in driver["rules"]:
            assert r["shortDescription"]["text"]
        assert len(run["results"]) == rep["finding_count"]
        for res in run["results"]:
            assert res["ruleId"] in rule_ids
            assert res["message"]["text"]
            loc = res["locations"][0]["physicalLocation"]
            assert loc["artifactLocation"]["uri"] == self.FIXTURE[0]
            assert loc["region"]["startLine"] >= 1

    def test_sarif_cli_writes_the_log(self, tmp_path):
        import lint_report

        out = tmp_path / "lint.sarif"
        rc = lint_report.main(
            self.FIXTURE + ["--no-allowlist", "--sarif", str(out),
                            "-o", str(tmp_path / "lint.json")])
        assert rc == 1  # the fixture has findings by design
        log = json.loads(out.read_text())
        assert log["version"] == "2.1.0"
        assert log["runs"][0]["results"]

    def test_suppressed_findings_carry_suppressions(self, tmp_path):
        import lint_report

        allow = tmp_path / "allow.json"
        allow.write_text(json.dumps([{
            "rule": "EV001", "path": self.FIXTURE[0],
            "symbol": "read_knob", "reason": "fixture exercise"}]))
        rep = lint_report.build_report(paths=self.FIXTURE,
                                       allowlist_path=str(allow))
        log = lint_report.to_sarif(rep)
        results = log["runs"][0]["results"]
        flagged = [r for r in results if "suppressions" in r]
        assert len(flagged) == 1
        assert flagged[0]["suppressions"][0]["kind"] == "external"

    def test_lint_ledger_row_gates_finding_count(self):
        import bench_compare

        def row(count, wall):
            return {"schema": 1, "kind": "lint", "device": "cpu",
                    "tiny": True, "metrics": {
                        "lint_finding_count": count,
                        "lint_wall_time_s": wall,
                        "lint_modules": 84}}

        # wall time is trajectory-only: doubling it alone stays clean
        ok = bench_compare.compare(row(0, 4.0), row(0, 9.0))
        assert ok["ok"] is True
        # the finding count has zero tolerance
        bad = bench_compare.compare(row(0, 4.0), row(1, 4.0))
        assert bad["ok"] is False
        assert bad["regressions"] == ["lint_finding_count"]


class TestAlertReport:
    @staticmethod
    def _doc(fps=0, missed=False):
        steady_fired = ["queue_wait_anomaly"] if fps else []
        kill_fired = [] if missed else ["error_rate_anomaly"]
        phases = [
            {"name": "steady", "expected": [], "fired": steady_fired,
             "false_positives": len(steady_fired), "detected": None},
            {"name": "chaos_kill",
             "expected": ["error_rate_anomaly", "worker_flap"],
             "fired": kill_fired, "false_positives": 0,
             "detected": bool(kill_fired)},
            {"name": "chaos_stall", "expected": ["watchdog_stall"],
             "fired": ["watchdog_stall"], "false_positives": 0,
             "detected": True},
        ]
        detected = sum(1 for p in phases if p["detected"])
        faults = 2
        return {
            "device": "cpu",
            "validation": {
                "phases": phases,
                "alert_false_positives": len(steady_fired),
                "false_positive_rules": steady_fired,
                "faults": faults,
                "detected": detected,
                "alert_recall": detected / faults,
            },
            "history": [
                {"rule": "watchdog_stall", "from": "pending",
                 "to": "firing", "t": 1.0, "value": 1.0,
                 "detail": "window increase 1 vs 1"},
                {"rule": "watchdog_stall", "from": "firing", "to": "ok",
                 "t": 2.0, "value": 0.0, "detail": "aged out"},
            ],
        }

    def test_rule_scores_arithmetic(self):
        import alert_report

        scores = alert_report.rule_scores(self._doc()["validation"]
                                          ["phases"])
        # fired in its expected window, never in steady
        assert scores["error_rate_anomaly"] == {
            "true_positives": 1, "false_positives": 0,
            "fault_windows": 1, "precision": 1.0, "recall": 1.0}
        # expected but silent: sibling covered the window, still recall 0
        # for the rule itself
        assert scores["worker_flap"]["recall"] == 0.0
        assert scores["worker_flap"]["precision"] is None
        fp = alert_report.rule_scores(self._doc(fps=1)["validation"]
                                      ["phases"])
        assert fp["queue_wait_anomaly"]["false_positives"] == 1
        assert fp["queue_wait_anomaly"]["precision"] == 0.0

    def test_main_exit_codes(self, tmp_path, capsys):
        import alert_report

        clean = tmp_path / "BENCH_alerts.json"
        clean.write_text(json.dumps(self._doc()))
        assert alert_report.main([str(clean)]) == 0
        out = capsys.readouterr().out
        assert "CLEAN" in out and "DETECTED" in out
        assert "firing history" in out

        assert alert_report.main([str(clean), "--json"]) == 0
        digest = json.loads(capsys.readouterr().out)
        assert digest["alert_recall"] == 1.0

        fp = tmp_path / "fp.json"
        fp.write_text(json.dumps(self._doc(fps=1)))
        assert alert_report.main([str(fp)]) == 1
        assert "FALSE POSITIVE" in capsys.readouterr().out

        miss = tmp_path / "miss.json"
        miss.write_text(json.dumps(self._doc(missed=True)))
        assert alert_report.main([str(miss)]) == 1
        assert "MISSED" in capsys.readouterr().out

        (tmp_path / "garbage.json").write_text("{not json")
        assert alert_report.main([str(tmp_path / "garbage.json")]) == 2
        assert alert_report.main([str(tmp_path / "missing.json")]) == 2
        # an artifact from a bench that died before phase validation
        (tmp_path / "dead.json").write_text(json.dumps({"device": "cpu"}))
        assert alert_report.main([str(tmp_path / "dead.json")]) == 2


class TestFedReport:
    @staticmethod
    def _fleet_doc(stale=False):
        return {
            "enabled": True, "stale_after_s": 0.5, "ticks": 4,
            "polls_total": 8, "poll_failures_total": 2 if stale else 0,
            "daemon": False,
            "workers": {
                "alpha": {"polls": 4, "failures": 0, "staleness_s": 0.05,
                          "stale": False, "rtt_s": 0.01,
                          "last_error": None, "error_rate": 0.0,
                          "queue_wait_p95_s": 0.2},
                "victim": {"polls": 4, "failures": 2 if stale else 0,
                           "staleness_s": 1.4 if stale else 0.06,
                           "stale": stale, "rtt_s": 0.01,
                           "last_error": ("ConnectionError: refused"
                                          if stale else None),
                           "error_rate": 1.0 if stale else 0.0,
                           "queue_wait_p95_s": None},
            },
            "fleet": {"queue_wait_p95_s": 0.2,
                      "error_rate": 0.5 if stale else 0.0,
                      "worker_stale_count": 1.0 if stale else 0.0},
        }

    @staticmethod
    def _snapshot_doc(stale=False):
        tail = 5.0 if stale else 0.1
        return {
            "schema": 1, "points": 512, "saved_t_mono": 100.0,
            "series": {
                "worker:alpha/staleness_s": [[t, 0.1] for t in range(8)],
                "worker:alpha/error_rate": [[t, 0.0] for t in range(8)],
                "worker:alpha/queue_wait_p95_s":
                    [[t, 0.2] for t in range(8)],
                "worker:victim/staleness_s":
                    [[t, 0.1] for t in range(6)] + [[6, tail], [7, tail]],
                "worker:victim/error_rate": [[t, 0.0] for t in range(8)],
                "fleet/queue_wait_p95_s": [[7, 0.2]],
                "fleet/error_rate": [[7, 0.0]],
                "fleet/worker_stale_count":
                    [[7, 1.0 if stale else 0.0]],
            },
        }

    def test_sparkline_shapes(self):
        import fed_report

        assert fed_report.sparkline([]) == "-"
        flat = fed_report.sparkline([1.0, 1.0, 1.0])
        assert flat == fed_report.SPARK[1] * 3
        ramp = fed_report.sparkline([0.0, 1.0])
        assert ramp[0] == fed_report.SPARK[0]
        assert ramp[-1] == fed_report.SPARK[-1]
        # trailing-window trim
        assert len(fed_report.sparkline(range(100))) == 16

    def test_build_summary_fleet_doc(self):
        import fed_report

        summary = fed_report.build_summary(self._fleet_doc(stale=True))
        assert summary["kind"] == "fleet"
        assert summary["stale_workers"] == ["victim"]
        assert summary["stale_after_s"] == 0.5
        by_name = {r["worker"]: r for r in summary["workers"]}
        assert not by_name["alpha"]["stale"]
        assert by_name["victim"]["error_rate"] == 1.0

    def test_build_summary_snapshot_doc(self):
        import fed_report

        summary = fed_report.build_summary(self._snapshot_doc(stale=True),
                                           stale_after_s=3.0)
        assert summary["kind"] == "snapshot"
        assert summary["stale_workers"] == ["victim"]
        by_name = {r["worker"]: r for r in summary["workers"]}
        # sparkline drawn from the staleness history
        assert len(by_name["victim"]["sparklines"]["staleness_s"]) == 8
        assert summary["fleet"]["worker_stale_count"] == 1.0

    def test_main_exit_codes(self, tmp_path, capsys):
        import fed_report

        clean = tmp_path / "fleet.json"
        clean.write_text(json.dumps(self._fleet_doc()))
        assert fed_report.main([str(clean)]) == 0
        out = capsys.readouterr().out
        assert "alpha" in out and "victim" in out

        assert fed_report.main([str(clean), "--json"]) == 0
        digest = json.loads(capsys.readouterr().out)
        assert digest["stale_workers"] == []

        stale = tmp_path / "stale.json"
        stale.write_text(json.dumps(self._fleet_doc(stale=True)))
        assert fed_report.main([str(stale)]) == 1
        err = capsys.readouterr().err
        assert "stale worker" in err and "victim" in err

        snap = tmp_path / "tsdb_snapshot.json"
        snap.write_text(json.dumps(self._snapshot_doc(stale=True)))
        assert fed_report.main([str(snap), "--stale-after", "3.0"]) == 1
        assert fed_report.main([str(snap), "--stale-after", "10.0"]) == 0

        (tmp_path / "garbage.json").write_text("{not json")
        assert fed_report.main([str(tmp_path / "garbage.json")]) == 2
        assert fed_report.main([str(tmp_path / "missing.json")]) == 2
        # a document that is neither summary nor snapshot
        (tmp_path / "other.json").write_text(json.dumps({"device": "cpu"}))
        assert fed_report.main([str(tmp_path / "other.json")]) == 2


class TestAotReport:
    """tools/aot_report.py: manifest rendering + divergence gate over
    the AOT artifact store (serving/aot.py)."""

    def _store(self, tmp_path):
        from stable_diffusion_webui_distributed_tpu.serving import (
            aot as aot_mod,
        )

        store = aot_mod.AotStore(str(tmp_path))
        store.save("('chunk', 'k1')", "d0=f32[1]", "chunk", b"exe-one")
        store.save("('encode', 'k2')", "d0=i32[77]", "encode", b"exe-two")
        return store

    def test_report_renders_cells_and_totals(self, tmp_path):
        import aot_report

        self._store(tmp_path)
        report = aot_report.build_report(str(tmp_path))
        assert report["ok"] and report["cell_count"] == 2
        assert report["by_kind"]["chunk"]["cells"] == 1
        assert report["total_bytes"] == len(b"exe-one") + len(b"exe-two")
        assert all(c["fingerprint_match"] for c in report["cells"])
        assert report["divergent"] == [] and report["orphans"] == []

    def test_exit_codes_gate_divergence(self, tmp_path, capsys):
        import aot_report

        store = self._store(tmp_path)
        assert aot_report.main(["--dir", str(tmp_path)]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["cell_count"] == 2

        # damage one artifact: content hash diverges -> rc 1
        (cell,) = [c for c in store.manifest()["cells"].values()
                   if c["kind"] == "chunk"]
        (tmp_path / cell["file"]).write_bytes(b"bit-flipped")
        assert aot_report.main(["--dir", str(tmp_path)]) == 1
        capsys.readouterr()

        # an unclaimed artifact on disk is divergence too
        (tmp_path / cell["file"]).write_bytes(b"exe-one")
        (tmp_path / "feedface.aotx").write_bytes(b"orphan")
        assert aot_report.main(["--dir", str(tmp_path)]) == 1
        capsys.readouterr()

        assert aot_report.main(["--dir",
                                str(tmp_path / "missing-root")]) == 2

    def test_output_file_and_fingerprint_mismatch_note(self, tmp_path,
                                                       capsys):
        import aot_report
        from stable_diffusion_webui_distributed_tpu.serving import (
            aot as aot_mod,
        )

        alien = aot_mod.AotStore(
            str(tmp_path), fingerprint={"jax": "elsewhere"})
        alien.save("('chunk', 'k1')", "d0=f32[1]", "chunk", b"exe")
        out_path = tmp_path / "aot.json"
        assert aot_report.main(["--dir", str(tmp_path),
                                "-o", str(out_path)]) == 0
        capsys.readouterr()
        report = json.loads(out_path.read_text())
        # coherent store, but the cell was built on another runtime:
        # the report flags it so an operator sees hydration will miss
        assert report["ok"]
        assert report["cells"][0]["fingerprint_match"] is False

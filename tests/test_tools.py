"""Tests for the chip-window tooling: the relay triage (the round-5
diagnosis layer bench.py's rc=3 reporting depends on) and the sweep's
wedge contract. All socket behavior is synthesized locally — no TPU, no
relay, no jax."""

import json
import socket
import subprocess
import sys
import threading

import pytest

sys.path.insert(0, "tools")

import tpu_claim_probe  # noqa: E402  (tools/ on path)


class _FakeRelay:
    """A localhost listener with pluggable accept behavior."""

    def __init__(self, mode):
        self.mode = mode            # "dead" = accept+close, "alive" = hold
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._held = []
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        self.sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            if self.mode == "dead":
                conn.close()        # instant EOF — the round-5 wedge
            else:
                self._held.append(conn)  # hold open like a live server

    def close(self):
        self._stop.set()
        self.thread.join(timeout=2)
        for c in self._held:
            c.close()
        self.sock.close()


@pytest.fixture
def patch_ports(monkeypatch):
    def _patch(port):
        monkeypatch.setattr(tpu_claim_probe, "RELAY_PORTS", (port,))
    return _patch


class TestTriage:
    def test_relay_dead_detected(self, patch_ports):
        relay = _FakeRelay("dead")
        try:
            patch_ports(relay.port)
            out = tpu_claim_probe.triage_relay(peek_s=1.0)
            entry = out[relay.port]
            assert entry["connect"] is True
            assert entry["instant_eof"] is True
            res = tpu_claim_probe.diagnose(triage_only=True)
            assert res["verdict"] == "relay-dead"
        finally:
            relay.close()

    def test_relay_alive_holds_connection(self, patch_ports):
        relay = _FakeRelay("alive")
        try:
            patch_ports(relay.port)
            out = tpu_claim_probe.triage_relay(peek_s=0.5)
            entry = out[relay.port]
            assert entry["connect"] is True
            assert entry["instant_eof"] is False
            res = tpu_claim_probe.diagnose(triage_only=True)
            assert res["verdict"] == "relay-alive-unprobed"
        finally:
            relay.close()

    def test_relay_down_detected(self, patch_ports):
        # grab a port, then close it so nothing is listening
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        patch_ports(port)
        res = tpu_claim_probe.diagnose(triage_only=True)
        assert res["verdict"] == "relay-down"

    def test_cli_exit_codes(self):
        """SDTPU_PROBE_PORTS points the REAL CLI at the synthetic dead
        relay: the rc=7 relay-dead path is pinned end-to-end."""
        relay = _FakeRelay("dead")
        try:
            proc = subprocess.run(
                [sys.executable, "tools/tpu_claim_probe.py", "--triage-only",
                 "--json"],
                capture_output=True, text=True,
                env={"PATH": "/usr/bin:/bin",
                     "SDTPU_PROBE_PORTS": str(relay.port)})
        finally:
            relay.close()
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["verdict"] == "relay-dead", (out, proc.stderr)
        assert proc.returncode == 7
        assert out["relay"][str(relay.port)]["instant_eof"] is True


class TestSweepWedgeContract:
    def test_is_wedge_classification(self):
        sys.path.insert(0, "tools")
        import sweep

        assert sweep._is_wedge({}, 3) is True            # init watchdog
        assert sweep._is_wedge(
            {"error": "ConnectionError: Connection refused"}, 1) is True
        assert sweep._is_wedge({"error": "relay wedged mid-claim"}, 1) is True
        assert sweep._is_wedge({"error": "assert 2 == 3"}, 1) is False
        assert sweep._is_wedge({"value": 27.0}, 0) is False

    def test_cells_unpack(self):
        import sweep

        for name, cell in sweep.CELLS.items():
            cfg_n, pol_kwargs, chunk, *rest = cell
            assert 1 <= cfg_n <= 5, name
            assert isinstance(pol_kwargs, dict), name
            assert chunk > 0, name
            if rest:
                assert isinstance(rest[0], dict), name

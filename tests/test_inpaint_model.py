"""Inpainting-specialized checkpoint tests (9-channel UNet, ldm "hybrid"
conditioning): every webui worker in a reference fleet serves
sd-v1-5-inpainting-style models; here the engine concatenates
[latent, mask, masked-image latent] natively (engine.py inpaint_cond)."""

import numpy as np
import pytest

from stable_diffusion_webui_distributed_tpu.models import convert
from stable_diffusion_webui_distributed_tpu.models.configs import (
    FAMILIES,
    SD15_INPAINT,
    TINY,
    TINY_INPAINT,
)
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
    array_to_b64png,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)

from test_pipeline import init_params


def b64_image(value, w=32, h=32):
    return array_to_b64png(
        np.full((h, w, 3), value, np.uint8))


def b64_mask(w=32, h=32, half=True):
    m = np.zeros((h, w, 3), np.uint8)
    if half:
        m[:, : w // 2] = 255
    else:
        m[:] = 255
    return array_to_b64png(m)


class TestFamilies:
    def test_inpaint_property(self):
        assert SD15_INPAINT.inpaint and TINY_INPAINT.inpaint
        assert not TINY.inpaint
        for name in ("sd15-inpaint", "sd2-inpaint", "sdxl-inpaint",
                     "tiny-inpaint"):
            assert name in FAMILIES

    def test_detect_family_by_conv_in(self):
        base = {"model.diffusion_model.input_blocks.0.0.weight":
                np.zeros((320, 9, 3, 3), np.float32)}
        assert convert.detect_family(base) == "sd15-inpaint"
        sd2 = dict(base)
        sd2["cond_stage_model.model.ln_final.weight"] = np.zeros(
            (1024,), np.float32)
        assert convert.detect_family(sd2) == "sd2-inpaint"
        four = {"model.diffusion_model.input_blocks.0.0.weight":
                np.zeros((320, 4, 3, 3), np.float32)}
        assert convert.detect_family(four) == "sd15"


class TestEngine:
    @pytest.fixture(scope="class")
    def engine(self):
        return Engine(TINY_INPAINT, init_params(TINY_INPAINT), chunk_size=4,
                      state=GenerationState())

    def test_txt2img_runs_and_is_deterministic(self, engine):
        p = GenerationPayload(prompt="a barn", steps=3, width=32, height=32,
                              seed=5)
        a = engine.txt2img(p)
        b = engine.txt2img(p)
        assert len(a.images) == 1 and a.images == b.images

    def test_img2img_mask_conditioning_changes_output(self, engine):
        base = dict(prompt="fix it", steps=4, width=32, height=32, seed=8,
                    init_images=[b64_image(128)],
                    denoising_strength=0.8, mask_blur=0)
        left = engine.img2img(GenerationPayload(**base, mask=b64_mask()))
        full = engine.img2img(GenerationPayload(
            **base, mask=b64_mask(half=False)))
        assert len(left.images) == 1
        # different masks change the hybrid conditioning AND the pinning,
        # so outputs must differ
        assert left.images[0] != full.images[0]
        again = engine.img2img(GenerationPayload(**base, mask=b64_mask()))
        assert again.images[0] == left.images[0]

    def test_plain_img2img_uses_blank_conditioning(self, engine):
        p = GenerationPayload(prompt="gray", steps=3, width=32, height=32,
                              seed=2, init_images=[b64_image(90)],
                              denoising_strength=0.7)
        a = engine.img2img(p)
        b = engine.img2img(p)
        assert a.images == b.images

    def test_range_split_seed_exact_on_inpaint_family(self, engine):
        p = GenerationPayload(prompt="cows", steps=3, width=32, height=32,
                              seed=31, batch_size=3)
        full = engine.txt2img(p)
        part = engine.generate_range(p, 1, 2)
        assert part.images == full.images[1:3]

    def test_hires_pass_runs(self, engine):
        p = GenerationPayload(prompt="up", steps=3, width=32, height=32,
                              seed=3, enable_hr=True, hr_scale=2.0,
                              denoising_strength=0.7)
        out = engine.txt2img(p)
        assert len(out.images) == 1

"""Recompile-free traced-LoRA serving (SDTPU_LORA_TRACED).

Fast tier (no pipeline compiles): the rank/slot bucketing ladder,
traced-set construction / zero-padding / content addressing, the batched
delta einsums against a numpy reference, heterogeneous row stacking, the
merge-latch regression (an identical partially-resolved set repeated
must be a no-op), the registry's mtime-validated adapter cache, the
group-key cell axes, the executables-census lora budget, warmup cell
parsing and the cache-key lora fold.

Slow tier (full TINY pipelines): traced output quality against the
merged reference, adapter-churn executable/merge stability with cache
survival, and batch-split identity under a traced set.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.models import lora as lora_mod
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.obs import perf as obs_perf
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (
    ServingDispatcher,
)

import quality


def make_lora_sd(rank=4, scale=0.3, seed=0, te=True):
    """Synthetic kohya adapter touching TINY's first UNet attn1 q and
    (optionally) the text encoder's layer-0 q projection."""
    rng = np.random.default_rng(seed)
    mods = [("lora_unet_input_blocks_1_1_transformer_blocks_0_attn1_to_q",
             32)]
    if te:
        mods.append(
            ("lora_te_text_model_encoder_layers_0_self_attn_q_proj", 32))
    sd = {}
    for module, d in mods:
        sd[f"{module}.lora_down.weight"] = (
            rng.standard_normal((rank, d)).astype(np.float32) * scale)
        sd[f"{module}.lora_up.weight"] = (
            rng.standard_normal((d, rank)).astype(np.float32) * scale)
        sd[f"{module}.alpha"] = np.float32(rank)
    return sd


def make_engine(loras, seed=0):
    return Engine(TINY, quality.init_params(TINY, seed=seed), chunk_size=4,
                  state=GenerationState(),
                  lora_provider=loras.get if loras is not None else None)


def payload(prompt, seed=3, steps=4, batch=1, **kw):
    return GenerationPayload(prompt=prompt, steps=steps, width=32,
                             height=32, seed=seed, batch_size=batch, **kw)


class TestLadder:
    def test_default_ladders_and_bucketing(self):
        assert lora_mod.rank_ladder() == (8, 16, 32, 64)
        assert lora_mod.slot_ladder() == (1, 2, 4)
        assert lora_mod.bucket_rank(1) == 8
        assert lora_mod.bucket_rank(8) == 8
        assert lora_mod.bucket_rank(9) == 16
        assert lora_mod.bucket_rank(64) == 64
        assert lora_mod.bucket_rank(65) is None
        assert lora_mod.bucket_slots(1) == 1
        assert lora_mod.bucket_slots(3) == 4
        assert lora_mod.bucket_slots(5) is None

    def test_env_ladder_override(self, monkeypatch):
        monkeypatch.setenv("SDTPU_LORA_RANKS", "4,12")
        monkeypatch.setenv("SDTPU_LORA_SLOTS", "2")
        assert lora_mod.rank_ladder() == (4, 12)
        assert lora_mod.bucket_rank(5) == 12
        assert lora_mod.bucket_slots(1) == 2
        assert lora_mod.bucket_slots(3) is None


class TestTracedSetBuild:
    def test_padding_and_content_address(self, monkeypatch):
        monkeypatch.setenv("SDTPU_LORA_TRACED", "1")
        params = quality.init_params(TINY)
        loras = {"a": make_lora_sd(seed=1), "b": make_lora_sd(seed=2)}
        ts = lora_mod.build_traced_set((("a", 0.8, 0.8),), loras.get,
                                       TINY, params)
        assert (ts.sig, ts.rank_bucket, ts.slots) == ("lora:r8s1", 8, 1)
        assert ts.applied == 2 and ts.skipped == 0
        site = ts.tree["unet"]["down_0_attn_0"]["block_0"]["attn1"]["qkv"]
        # rank 4 pads up to the 8-bucket; the padded tail must be exact 0
        assert site["down"].shape == (1, 8, 32)
        assert site["up"].shape == (1, 96, 8)
        assert np.all(site["down"][:, 4:, :] == 0)
        assert np.all(site["up"][:, :, 4:] == 0)
        # a site no adapter touches is all-zero (contributes exactly 0)
        off = ts.tree["unet"]["mid_attn"]["proj_in"]
        assert not np.any(off["down"])
        # content addressing: same specs reproduce, any change re-keys
        again = lora_mod.build_traced_set((("a", 0.8, 0.8),), loras.get,
                                          TINY, params)
        assert again.content == ts.content
        other = lora_mod.build_traced_set((("a", 1.0, 0.8),), loras.get,
                                          TINY, params)
        assert other.content != ts.content
        # this adapter carries TE factors, so the TE address is non-empty
        assert ts.te_content and ts.te_content != ts.content

    def test_two_slot_and_unresolvable(self, monkeypatch):
        monkeypatch.setenv("SDTPU_LORA_TRACED", "1")
        params = quality.init_params(TINY)
        loras = {"a": make_lora_sd(seed=1), "b": make_lora_sd(seed=2)}
        ts = lora_mod.build_traced_set(
            (("a", 0.8, 0.8), ("b", 1.0, 1.0)), loras.get, TINY, params)
        assert (ts.rank_bucket, ts.slots) == (8, 2)
        # an unknown name cannot ride traced — merged-path fallback
        assert lora_mod.build_traced_set(
            (("nope", 1.0, 1.0),), loras.get, TINY, params) is None
        # a rank past the ladder cannot ride either
        big = {"big": make_lora_sd(rank=96, seed=3)}
        assert lora_mod.build_traced_set(
            (("big", 1.0, 1.0),), big.get, TINY, params) is None

    def test_zero_set_is_exact_noop_contribution(self):
        params = quality.init_params(TINY)
        zs = lora_mod.zero_set(params, TINY, 8, 1)
        assert zs.sig == "lora:r8s1" and zs.content == "zero"
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (2, 5, 32)).astype(np.float32))
        site = zs.tree["unet"]["down_0_attn_0"]["proj_in"]
        assert not np.any(np.asarray(lora_mod.delta_out(x, site)))


class TestDeltaMath:
    def _site(self, rng, s, r, i, o, batched=None):
        shape_d = (s, r, i) if batched is None else (batched, s, r, i)
        shape_u = (s, o, r) if batched is None else (batched, s, o, r)
        return {
            "down": jnp.asarray(
                rng.standard_normal(shape_d).astype(np.float32)),
            "up": jnp.asarray(
                rng.standard_normal(shape_u).astype(np.float32)),
        }

    def test_shared_site_matches_numpy(self):
        rng = np.random.default_rng(0)
        site = self._site(rng, s=2, r=4, i=8, o=6)
        x = jnp.asarray(rng.standard_normal((3, 5, 8)).astype(np.float32))
        got = np.asarray(lora_mod.delta_out(x, site))
        want = np.zeros((3, 5, 6), np.float32)
        for s in range(2):
            want += np.asarray(x) @ np.asarray(site["down"][s]).T \
                @ np.asarray(site["up"][s]).T
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_per_row_site_matches_rowwise(self):
        rng = np.random.default_rng(1)
        site = self._site(rng, s=2, r=4, i=8, o=6, batched=3)
        x = jnp.asarray(rng.standard_normal((3, 5, 8)).astype(np.float32))
        got = np.asarray(lora_mod.delta_out(x, site))
        for b in range(3):
            row_site = {"down": site["down"][b], "up": site["up"][b]}
            row = np.asarray(lora_mod.delta_out(x[b:b + 1], row_site))
            np.testing.assert_allclose(got[b:b + 1], row,
                                       rtol=2e-5, atol=2e-5)

    def test_apply_site_adds_delta_and_passes_through(self):
        rng = np.random.default_rng(2)
        site = self._site(rng, s=1, r=4, i=8, o=6)
        x = jnp.asarray(rng.standard_normal((2, 5, 8)).astype(np.float32))
        y = jnp.asarray(rng.standard_normal((2, 5, 6)).astype(np.float32))
        out = lora_mod.apply_site(y, x, {"k": site}, "k")
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(y) + np.asarray(lora_mod.delta_out(x, site)),
            rtol=2e-5, atol=2e-5)
        assert lora_mod.apply_site(y, x, None, "k") is y
        assert lora_mod.apply_site(y, x, {"other": site}, "k") is y


class TestStackRows:
    def test_heterogeneous_rows_stack_and_pad(self, monkeypatch):
        monkeypatch.setenv("SDTPU_LORA_TRACED", "1")
        params = quality.init_params(TINY)
        loras = {"a": make_lora_sd(seed=1), "b": make_lora_sd(seed=2)}
        ta = lora_mod.build_traced_set((("a", 0.8, 0.8),), loras.get,
                                       TINY, params)
        tb = lora_mod.build_traced_set((("b", 1.0, 1.0),), loras.get,
                                       TINY, params)
        st = lora_mod.stack_row_sets([ta, tb], 2)
        site = st["unet"]["down_0_attn_0"]["block_0"]["attn1"]["qkv"]
        assert site["down"].shape == (2, 1, 8, 32)
        np.testing.assert_array_equal(
            site["down"][0],
            ta.tree["unet"]["down_0_attn_0"]["block_0"]["attn1"]["qkv"]
            ["down"])
        np.testing.assert_array_equal(
            site["down"][1],
            tb.tree["unet"]["down_0_attn_0"]["block_0"]["attn1"]["qkv"]
            ["down"])
        # a short list self-pads to the batch by repeating its last row
        padded = lora_mod.stack_row_sets([ta], 3)
        p = padded["unet"]["down_0_attn_0"]["block_0"]["attn1"]["qkv"]
        assert p["down"].shape[0] == 3
        np.testing.assert_array_equal(p["down"][1], p["down"][0])
        np.testing.assert_array_equal(p["down"][2], p["down"][0])

    def test_mixed_cells_refused(self, monkeypatch):
        monkeypatch.setenv("SDTPU_LORA_TRACED", "1")
        params = quality.init_params(TINY)
        loras = {"a": make_lora_sd(seed=1), "b": make_lora_sd(seed=2)}
        one = lora_mod.build_traced_set((("a", 0.8, 0.8),), loras.get,
                                        TINY, params)
        two = lora_mod.build_traced_set(
            (("a", 0.8, 0.8), ("b", 1.0, 1.0)), loras.get, TINY, params)
        with pytest.raises(AssertionError):
            lora_mod.stack_row_sets([one, two], 2)


class _CountingProvider:
    """Registry stand-in: counts lookups, exposes the reload generation
    the engine's merge latch keys on."""

    def __init__(self, loras):
        self.loras = loras
        self.lora_generation = 0
        self.calls = 0

    def provider(self, name):
        self.calls += 1
        return self.loras.get(name)


class TestMergeLatchRegression:
    def test_identical_unresolved_set_is_noop(self):
        # Regression for the _UNRESOLVED latch: a set with one skipped
        # name used to defeat the latch entirely, re-merging from base on
        # EVERY request. The resolved outcome (skips included) is now
        # latched, so an identical repeat touches neither the provider
        # nor the param tree.
        src = _CountingProvider({"good": make_lora_sd(seed=1)})
        eng = Engine(TINY, quality.init_params(TINY), chunk_size=4,
                     state=GenerationState(), lora_provider=src.provider)
        specs = (("good", 1.0, 1.0), ("nope", 1.0, 1.0))
        eng.set_loras(specs)
        assert eng._lora_merge_total == 1
        calls, epoch = src.calls, eng._model_epoch
        eng.set_loras(specs)
        assert eng._lora_merge_total == 1
        assert src.calls == calls
        assert eng._model_epoch == epoch

    def test_provider_generation_retries_skips(self):
        # /refresh-loras bumps the generation: the SAME specs must
        # re-resolve exactly once (the file may exist now), not never.
        src = _CountingProvider({"good": make_lora_sd(seed=1)})
        eng = Engine(TINY, quality.init_params(TINY), chunk_size=4,
                     state=GenerationState(), lora_provider=src.provider)
        specs = (("good", 1.0, 1.0), ("late", 1.0, 1.0))
        eng.set_loras(specs)
        assert eng._lora_merge_total == 1
        src.loras["late"] = make_lora_sd(seed=2)
        eng.set_loras(specs)  # same generation: still latched
        assert eng._lora_merge_total == 1
        src.lora_generation += 1
        eng.set_loras(specs)  # rescan: retries, both resolve now
        assert eng._lora_merge_total == 3

    def test_empty_set_after_rescan_stays_cheap(self):
        src = _CountingProvider({})
        eng = Engine(TINY, quality.init_params(TINY), chunk_size=4,
                     state=GenerationState(), lora_provider=src.provider)
        eng.set_loras((("nope", 1.0, 1.0),))
        epoch = eng._model_epoch
        src.lora_generation += 1
        # already pristine: a rescan can't change "no adapters", so the
        # latch refreshes without the cache-retiring epoch bump
        eng.set_loras(())
        assert eng._model_epoch == epoch + 1  # the unlatch restored base
        eng.set_loras(())
        assert eng._model_epoch == epoch + 1


class TestRegistryAdapterCache:
    def _registry(self, tmp_path):
        from stable_diffusion_webui_distributed_tpu.pipeline.registry \
            import ModelRegistry

        return ModelRegistry(model_dir=str(tmp_path))

    def _write_adapter(self, path, seed=1):
        from safetensors.numpy import save_file

        sd = make_lora_sd(seed=seed)
        save_file({k: np.asarray(v) for k, v in sd.items()}, path)

    def test_mtime_invalidation_reloads(self, tmp_path):
        reg = self._registry(tmp_path)
        path = str(tmp_path / "a.safetensors")
        self._write_adapter(path)
        reg._lora_paths = {"a": path}
        sd1 = reg.lora_provider("a")
        assert sd1 is not None
        assert reg.lora_provider("a") is sd1  # cached: same object
        # edit the file in place: the stale mtime must force a reload
        st = os.stat(path)
        os.utime(path, (st.st_atime + 5, st.st_mtime + 5))
        sd2 = reg.lora_provider("a")
        assert sd2 is not sd1
        assert reg.lora_provider("nope") is None

    def test_byte_cap_disables_retention(self, tmp_path):
        reg = self._registry(tmp_path)
        path = str(tmp_path / "a.safetensors")
        self._write_adapter(path)
        reg._lora_paths = {"a": path}
        reg._lora_cache.max_bytes = 1  # nothing fits: loads still serve
        sd1 = reg.lora_provider("a")
        sd2 = reg.lora_provider("a")
        assert sd1 is not None and sd2 is not None and sd2 is not sd1

    def test_refresh_bumps_generation_and_drops_cache(self, tmp_path):
        reg = self._registry(tmp_path)
        path = str(tmp_path / "a.safetensors")
        self._write_adapter(path)
        reg._lora_paths = {"a": path}
        sd1 = reg.lora_provider("a")
        gen = reg.lora_generation
        reg.refresh()
        assert reg.lora_generation == gen + 1
        reg._lora_paths = {"a": path}  # the empty scan dropped it
        assert reg.lora_provider("a") is not sd1


class TestGroupKeyCells:
    def test_gate_off_tagged_keys_adapterless_cell(self):
        p = payload("a cow <lora:a:0.8>")
        key = ServingDispatcher._group_key(None, p)
        assert len(key) == 14
        assert key[-3:-1] == (0, 0)
        assert isinstance(key[-1], str)
        # tagless payloads share the cell — adapterless grouping intact
        assert ServingDispatcher._group_key(None, payload("a cow"))[-3:-1] \
            == (0, 0)

    def test_rowspec_cells(self, monkeypatch):
        import types

        assert ServingDispatcher._traced_rowspec(None, payload("x")) \
            == (0, 0)
        tagged = payload("x <lora:a:0.8>")
        assert ServingDispatcher._traced_rowspec(None, tagged) is None
        monkeypatch.setenv("SDTPU_LORA_TRACED", "1")
        # engineless (ETA probes): merged-path conservatism
        assert ServingDispatcher._traced_rowspec(None, tagged) is None
        stub = types.SimpleNamespace(engine=types.SimpleNamespace(
            _traced_set_for=lambda specs: types.SimpleNamespace(
                rank_bucket=16, slots=2)))
        assert ServingDispatcher._traced_rowspec(stub, tagged) == (16, 2)
        # the adaptive sampler's attempt executable has no delta args
        adaptive = payload("x <lora:a:0.8>",
                           sampler_name="DPM adaptive")
        assert ServingDispatcher._traced_rowspec(stub, adaptive) is None


class TestCensusLoraBudget:
    def _keys(self, sigs):
        keys = []
        for i, sig in enumerate(sigs):
            for sc in (1, 2):
                keys.append(("chunk", "Euler a", 8, 64, 64, 1, sig,
                             sc, "bf16"))
        return keys

    def test_ladder_cells_within_budget_stay_silent(self):
        sigs = ["", "lora:r8s1", "lora:r16s1", "lora:r32s2", "lora:r64s4"]
        census = obs_perf.census_from_keys(self._keys(sigs))
        assert not census["alarm"]
        assert census["budget"]["lora"] == obs_perf.LORA_BUDGET == 4
        assert census["buckets"][0]["lora_variants"] == 4

    def test_cell_explosion_alarms(self):
        sigs = ["", "lora:r8s1", "lora:r8s2", "lora:r16s1", "lora:r16s2",
                "lora:r32s1"]
        census = obs_perf.census_from_keys(self._keys(sigs))
        assert census["alarm"]

    def test_legacy_keys_census_unchanged(self):
        # pre-lora key layout (no sig axis): nothing looks like a sig,
        # nothing is attributed to the lora axis
        keys = [("chunk", "Euler a", 8, 64, 64, 1, sc, "bf16")
                for sc in (1, 2)]
        census = obs_perf.census_from_keys(keys)
        assert not census["alarm"]
        assert census["buckets"][0]["lora_variants"] == 0


class TestWarmupCells:
    def test_parse_and_bucket(self, monkeypatch):
        from stable_diffusion_webui_distributed_tpu.serving.warmup import (
            _warmup_lora_cells,
        )

        monkeypatch.setenv("SDTPU_LORA_TRACED", "1")
        monkeypatch.setenv("SDTPU_WARMUP_LORA",
                           "r16s1, r10s3,junk,r999s1,r16s1")
        assert _warmup_lora_cells() == [None, (16, 1), (16, 4)]
        monkeypatch.setenv("SDTPU_WARMUP_LORA", "r16s1")
        monkeypatch.delenv("SDTPU_LORA_TRACED")
        assert _warmup_lora_cells() == [None]


class TestCacheKeyFold:
    def test_empty_lora_preserves_digests(self):
        from stable_diffusion_webui_distributed_tpu.cache import keys as K

        fp = ("tiny", 0, 0)
        assert K.embed_key("a cow", 0, 1, fp) == \
            K.embed_key("a cow", 0, 1, fp, lora="")
        assert K.embed_key("a cow", 0, 1, fp, lora="x") != \
            K.embed_key("a cow", 0, 1, fp)
        p = payload("a cow")
        assert K.result_key(p, fp, "txt2img") == \
            K.result_key(p, fp, "txt2img", lora="")
        assert K.result_key(p, fp, "txt2img", lora="x") != \
            K.result_key(p, fp, "txt2img")
        kw = dict(model_fp=fp, batch=1, width=32, height=32, steps=4,
                  cadence=1, sc_active=False, precision="bf16")
        assert K.prefix_key(p, **kw) == K.prefix_key(p, lora="", **kw)
        assert K.prefix_key(p, lora="x", **kw) != K.prefix_key(p, **kw)


@pytest.mark.slow
class TestTracedPipeline:
    def test_traced_matches_merged_quality(self, monkeypatch):
        loras = {"a": make_lora_sd(seed=1)}
        p = payload("a cow <lora:a:0.8>")
        merged_eng = make_engine(loras)
        ref = merged_eng.txt2img(p)
        assert merged_eng._lora_merge_total >= 1
        monkeypatch.setenv("SDTPU_LORA_TRACED", "1")
        traced_eng = make_engine(loras)
        out = traced_eng.txt2img(p)
        assert traced_eng._lora_merge_total == 0
        assert traced_eng._traced_lora is not None
        assert quality.mean_psnr(ref.images, out.images) >= 28.0
        assert quality.mean_ssim(ref.images, out.images) >= 0.985
        # and the adapter genuinely changes the output
        plain = traced_eng.txt2img(payload("a cow"))
        assert plain.images[0] != out.images[0]

    def test_churn_mints_no_executables_and_no_merges(self, monkeypatch):
        monkeypatch.setenv("SDTPU_LORA_TRACED", "1")
        loras = {n: make_lora_sd(seed=i + 1)
                 for i, n in enumerate(("a", "b", "c"))}
        eng = make_engine(loras)
        base = eng.txt2img(payload("a cow", seed=3))
        first = eng.txt2img(payload("a cow <lora:a:0.8>", seed=3))
        n_exec = len(eng.executable_keys())
        outs = {}
        for i, n in enumerate(("b", "c", "a", "b")):
            outs[(i, n)] = eng.txt2img(
                payload(f"a cow <lora:{n}:0.8>", seed=3))
        # THE tentpole claim: adapter switches are compile-free,
        # merge-free, and epoch-free
        assert len(eng.executable_keys()) == n_exec
        assert eng._lora_merge_total == 0
        census = obs_perf.census_from_keys(eng.executable_keys())
        assert not census["alarm"]
        # content actually switches: distinct adapters, distinct pixels;
        # the same adapter reproduces bit-exactly across the churn
        assert outs[(0, "b")].images[0] != outs[(1, "c")].images[0]
        assert outs[(3, "b")].images[0] == outs[(0, "b")].images[0]
        assert outs[(2, "a")].images[0] == first.images[0]
        # and the pristine tree never moved: tagless still matches base
        again = eng.txt2img(payload("a cow", seed=3))
        assert again.images[0] == base.images[0]

    def test_batch_split_identity_under_traced_set(self, monkeypatch):
        monkeypatch.setenv("SDTPU_LORA_TRACED", "1")
        loras = {"a": make_lora_sd(seed=1)}
        eng = make_engine(loras)
        p = payload("a cow <lora:a:0.8>", batch=2)
        full = eng.txt2img(p)
        assert eng._lora_merge_total == 0
        eng.state.begin_request()
        lo = eng.generate_range(p, 0, 1)
        hi = eng.generate_range(p, 1, 1)
        # the worker-side fan-out unit: per-image bytes must not depend
        # on which sub-range (or batch row) carried the traced factors
        assert lo.images[0] == full.images[0]
        assert hi.images[0] == full.images[1]

"""Native PNG encoder tests: build, correctness vs PIL decode, fallback."""

import io

import numpy as np
import pytest

from stable_diffusion_webui_distributed_tpu.runtime import native
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    array_to_b64png, b64png_to_array,
)

RNG = np.random.default_rng(11)


class TestNativePng:
    def test_roundtrip_via_pil(self):
        img = RNG.integers(0, 256, (48, 64, 3), np.uint8)
        data = native.encode_png(img)
        if data is None:
            pytest.skip("native toolchain unavailable")
        from PIL import Image

        decoded = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
        np.testing.assert_array_equal(decoded, img)

    def test_rgba(self):
        img = RNG.integers(0, 256, (16, 16, 4), np.uint8)
        data = native.encode_png(img)
        if data is None:
            pytest.skip("native toolchain unavailable")
        from PIL import Image

        decoded = np.asarray(Image.open(io.BytesIO(data)))
        np.testing.assert_array_equal(decoded, img)

    def test_invalid_inputs_return_none(self):
        assert native.encode_png(np.zeros((4, 4), np.uint8)) is None
        assert native.encode_png(np.zeros((4, 4, 3), np.float32)) is None

    def test_payload_helper_roundtrip(self):
        # whichever path (native or PIL) serves array_to_b64png, the wire
        # format must decode back to the same pixels
        img = RNG.integers(0, 256, (32, 32, 3), np.uint8)
        b64 = array_to_b64png(img)
        np.testing.assert_array_equal(b64png_to_array(b64), img)

"""ESRGAN (RRDBNet) upscaler tests: key conversion for both checkpoint
layouts, x4 application, fractional-target resize, registry discovery and
the image-space hires path through the engine."""

import os

import numpy as np
import pytest

from stable_diffusion_webui_distributed_tpu.models import esrgan

RNG = np.random.default_rng(11)


def make_rrdb_sd(nf=8, gc=4, nb=2, old_arch=False):
    """Synthetic RRDBNet weights (tiny nf/gc/nb) in either key layout."""
    sd = {}

    def conv(name_new, name_old, cout, cin):
        w = RNG.standard_normal((cout, cin, 3, 3)).astype(np.float32) * 0.05
        b = RNG.standard_normal((cout,)).astype(np.float32) * 0.01
        key = name_old if old_arch else name_new
        sd[f"{key}.weight"] = w
        sd[f"{key}.bias"] = b

    conv("conv_first", "model.0", nf, 3)
    for i in range(nb):
        for j in range(1, 4):
            for k in range(1, 6):
                cin = nf + (k - 1) * gc
                cout = gc if k < 5 else nf
                conv(f"body.{i}.rdb{j}.conv{k}",
                     f"model.1.sub.{i}.RDB{j}.conv{k}.0", cout, cin)
    conv("conv_body", f"model.1.sub.{nb}", nf, nf)
    conv("conv_up1", "model.3", nf, nf)
    conv("conv_up2", "model.6", nf, nf)
    conv("conv_hr", "model.8", nf, nf)
    conv("conv_last", "model.10", 3, nf)
    return sd


class TestConversion:
    def test_new_arch_x4_shape(self):
        params = esrgan.convert_esrgan(make_rrdb_sd())
        img = RNG.random((1, 8, 8, 3)).astype(np.float32)
        out = np.asarray(esrgan.rrdbnet_apply(params, img))
        assert out.shape == (1, 32, 32, 3)
        assert np.isfinite(out).all()

    def test_old_arch_translates_to_same_network(self):
        global RNG
        RNG = np.random.default_rng(5)
        new_sd = make_rrdb_sd(old_arch=False)
        RNG = np.random.default_rng(5)  # identical weights, old keys
        old_sd = make_rrdb_sd(old_arch=True)
        p_new = esrgan.convert_esrgan(new_sd)
        p_old = esrgan.convert_esrgan(old_sd)
        img = np.random.default_rng(0).random((1, 6, 6, 3)).astype(
            np.float32)
        np.testing.assert_array_equal(
            np.asarray(esrgan.rrdbnet_apply(p_new, img)),
            np.asarray(esrgan.rrdbnet_apply(p_old, img)))

    def test_pixel_unshuffle_input_rejected(self):
        sd = make_rrdb_sd()
        sd["conv_first.weight"] = np.zeros((8, 12, 3, 3), np.float32)
        with pytest.raises(ValueError, match="12 channels"):
            esrgan.convert_esrgan(sd)

    def test_upscaler_hits_exact_fractional_target(self):
        params = esrgan.convert_esrgan(make_rrdb_sd())
        up = esrgan.make_upscaler(params)
        img = RNG.random((2, 8, 8, 3)).astype(np.float32)
        out = np.asarray(up(img, 20, 12))  # x4 then lanczos down to 20x12
        assert out.shape == (2, 12, 20, 3)
        assert out.min() >= 0.0 and out.max() <= 1.0
        again = np.asarray(up(img, 20, 12))
        np.testing.assert_array_equal(out, again)


class TestEngineHiresPath:
    def test_registry_discovers_and_engine_uses_image_upscaler(
            self, tmp_path):
        from safetensors.numpy import save_file

        from test_registry import write_tiny_checkpoint
        from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
            GenerationPayload,
        )
        from stable_diffusion_webui_distributed_tpu.pipeline.registry import (
            ModelRegistry,
        )
        from stable_diffusion_webui_distributed_tpu.runtime import dtypes
        from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
            GenerationState,
        )

        model_dir = str(tmp_path / "models")
        write_tiny_checkpoint(model_dir)
        os.makedirs(os.path.join(model_dir, "ESRGAN"))
        save_file(make_rrdb_sd(),
                  os.path.join(model_dir, "ESRGAN", "Tiny_x4plus.safetensors"))

        reg = ModelRegistry(model_dir, policy=dtypes.F32,
                            state=GenerationState())
        assert "Tiny_x4plus" in reg.available_upscalers()
        # webui-style display name resolves to the file
        assert reg.upscaler_provider("tiny x4plus") is not None
        assert reg.upscaler_provider("No Such Upscaler") is None

        # exact canonical match beats substring shadowing: with both
        # ..._x4plus and ..._x4plus_anime_6B present, the anime display
        # name must pick the anime file (registry.py exact-first tiers)
        save_file(make_rrdb_sd(),
                  os.path.join(model_dir, "ESRGAN",
                               "Tiny_x4plus_anime_6B.safetensors"))
        reg2 = ModelRegistry(model_dir, policy=dtypes.F32,
                             state=GenerationState())
        want = reg2.available_upscalers()["Tiny_x4plus_anime_6B"]
        assert reg2._resolve_upscaler_path("Tiny 4x+ Anime6B") == want
        assert reg2._resolve_upscaler_path("tiny x4plus") == \
            reg2.available_upscalers()["Tiny_x4plus"]

        engine = reg.activate("tinymodel")
        base = dict(prompt="u", steps=3, width=32, height=32, seed=6,
                    enable_hr=True, hr_scale=2.0, denoising_strength=0.7)
        esr = engine.txt2img(GenerationPayload(
            **base, hr_upscaler="Tiny_x4plus"))
        latent = engine.txt2img(GenerationPayload(**base))
        assert len(esr.images) == 1
        # the image-space path conditions the second pass differently
        assert esr.images[0] != latent.images[0]
        # determinism
        again = engine.txt2img(GenerationPayload(
            **base, hr_upscaler="Tiny_x4plus"))
        assert again.images[0] == esr.images[0]

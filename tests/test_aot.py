"""AOT executable artifacts (SDTPU_AOT, serving/aot.py) + warm engine
pool (SDTPU_POOL, fleet/pool.py).

The contract under test: a warm engine hydrates every compiled stage
from the artifact store byte-for-byte (zero fresh chunk compiles, same
images), a fingerprint mismatch or damaged artifact FALLS BACK to a
fresh compile (journaled, never a crash, never a wrong executable), and
with the gate off ``Engine._cached`` takes its pre-existing path —
hash-pinned through tests/goldens.json. The pool side: least-loaded
checkout, chaos-kill isolation (inflight work keeps its engine), heal
to target size, and autoscale decisions upgraded from ``no_executor``
to ``executed``/``failed`` in the audit ring.
"""

import os

import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.fleet import pool as fleet_pool
from stable_diffusion_webui_distributed_tpu.fleet.slices import (
    AutoscaleEngine, SliceInfo, SliceRegistry,
)
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.obs import journal as obs_journal
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.serving import aot as aot_mod
from stable_diffusion_webui_distributed_tpu.serving.metrics import METRICS
from test_goldens import _check
from test_pipeline import init_params


def payload(**kw):
    defaults = dict(prompt="an aot cow", steps=4, width=32, height=32,
                    seed=7, sampler_name="Euler a")
    defaults.update(kw)
    return GenerationPayload(**defaults)


def fresh_engine():
    return Engine(TINY, init_params(TINY), chunk_size=4,
                  state=GenerationState())


# -- unit plumbing over a tiny jit cell --------------------------------------

def _double_build():
    import jax

    return jax.jit(lambda x: x * 2.0)


def _cell(store):
    return aot_mod.AotFunction(("unit", "double"), _double_build,
                               store=store)


class TestStoreUnit:
    def test_miss_save_then_hit_across_instances(self, tmp_path):
        store = aot_mod.AotStore(str(tmp_path))
        x = jnp.arange(4.0)
        a = _cell(store)
        assert list(a(x)) == [0.0, 2.0, 4.0, 6.0]
        assert store.stats_snapshot() == {"hit": 0, "miss": 1,
                                          "saved": 1, "fallback": 0}
        # a "restarted process": same store dir, fresh everything
        store2 = aot_mod.AotStore(str(tmp_path))
        b = _cell(store2)
        assert list(b(x)) == list(a(x))
        assert store2.stats_snapshot()["hit"] == 1
        assert store2.stats_snapshot()["miss"] == 0

    def test_one_key_many_signatures(self, tmp_path):
        """One compile key hosts one executable PER call signature (the
        encode stage retraces per chunk count)."""
        store = aot_mod.AotStore(str(tmp_path))
        a = _cell(store)
        a(jnp.arange(4.0))
        a(jnp.arange(8.0))
        assert a.executable_count() == 2
        assert len(store.manifest()["cells"]) == 2

    def test_fingerprint_mismatch_falls_back_and_journals(
            self, tmp_path, monkeypatch):
        store = aot_mod.AotStore(str(tmp_path))
        x = jnp.arange(4.0)
        _cell(store)(x)  # populate
        alien = aot_mod.AotStore(
            str(tmp_path), fingerprint={"jax": "not-this-runtime"})
        assert alien.load(repr(("unit", "double")),
                          aot_mod.call_signature((x,), {}))[0] \
            == "fingerprint_mismatch"
        monkeypatch.setenv("SDTPU_JOURNAL", "1")
        obs_journal.JOURNAL.clear()
        c = _cell(alien)
        assert list(c(x)) == [0.0, 2.0, 4.0, 6.0]  # fell back to compile
        assert alien.stats_snapshot()["fallback"] == 1
        events = obs_journal.JOURNAL.snapshot()["events"]
        fb = [e for e in events if e["event"] == "aot_fallback"]
        assert fb and fb[0]["attrs"]["reason"] == "fingerprint_mismatch"

    def test_corrupt_artifact_falls_back_and_backfills(self, tmp_path):
        store = aot_mod.AotStore(str(tmp_path))
        x = jnp.arange(4.0)
        _cell(store)(x)
        (cell,) = store.manifest()["cells"].values()
        with open(tmp_path / cell["file"], "wb") as f:
            f.write(b"truncated garbage")  # content hash now diverges
        store2 = aot_mod.AotStore(str(tmp_path))
        c = _cell(store2)
        assert list(c(x)) == [0.0, 2.0, 4.0, 6.0]
        stats = store2.stats_snapshot()
        assert stats["fallback"] == 1 and stats["hit"] == 0
        assert stats["saved"] == 1  # the fresh compile re-filled the cell
        store3 = aot_mod.AotStore(str(tmp_path))
        _cell(store3)(x)
        assert store3.stats_snapshot()["hit"] == 1

    def test_damaged_manifest_is_an_empty_store(self, tmp_path):
        (tmp_path / aot_mod.MANIFEST_NAME).write_text("{not json")
        store = aot_mod.AotStore(str(tmp_path))
        assert store.manifest()["cells"] == {}
        assert _cell(store)(jnp.arange(4.0)) is not None
        assert store.stats_snapshot()["saved"] == 1

    def test_verify_flags_divergence_and_orphans(self, tmp_path):
        store = aot_mod.AotStore(str(tmp_path))
        _cell(store)(jnp.arange(4.0))
        assert store.verify()["ok"]
        (cell,) = store.manifest()["cells"].values()
        with open(tmp_path / cell["file"], "wb") as f:
            f.write(b"flip")
        v = store.verify()
        assert not v["ok"] and v["cells"][0]["status"] == "sha_mismatch"
        os.remove(tmp_path / cell["file"])
        assert store.verify()["cells"][0]["status"] == "missing"
        (tmp_path / ("deadbeef" + aot_mod.ARTIFACT_SUFFIX)).write_bytes(
            b"unclaimed")
        v = store.verify()
        assert v["orphans"] == ["deadbeef" + aot_mod.ARTIFACT_SUFFIX]


# -- the engine path ---------------------------------------------------------

class TestEngineHydration:
    def test_warm_engine_hydrates_byte_identical(self, tmp_path,
                                                 monkeypatch):
        """The acceptance bar: a restarted engine over a populated store
        compiles NOTHING (every stage deserializes) and produces the
        same image bytes."""
        monkeypatch.setenv("SDTPU_AOT", "1")
        monkeypatch.setenv("SDTPU_AOT_DIR", str(tmp_path))
        p = payload(seed=41)
        METRICS.clear()
        cold = fresh_engine().txt2img(p)
        s = METRICS.summary()
        assert s["compiles"].get("chunk") == 1
        assert not s["aot_loads"]
        METRICS.clear()
        warm = fresh_engine().txt2img(p)
        s = METRICS.summary()
        assert warm.images == cold.images
        assert warm.seeds == cold.seeds
        assert s["compiles"] == {}  # zero fresh compiles of ANY kind
        assert s["aot_loads"].get("chunk") == 1
        assert s["aot_loads"].get("encode") == 1
        store = aot_mod.get_store()
        assert store.verify()["ok"]
        manifest = store.manifest()
        kinds = {c["kind"] for c in manifest["cells"].values()}
        assert {"encode", "chunk"} <= kinds


class TestGateOff:
    def test_gate_off_golden_pin(self):
        """SDTPU_AOT=0 (the default) is hash-pinned: the AOT landing must
        leave the plain ``Engine._cached`` path byte-identical, and every
        later PR inherits the pin."""
        assert not aot_mod.enabled()
        p = payload(prompt="aot gate pin", seed=77, n_iter=2)
        _check("aot/gate-off", fresh_engine().txt2img(p))


# -- warm pool ---------------------------------------------------------------

class TestWarmPool:
    def _pool(self, size=2):
        made = []

        def factory(name):
            made.append(name)
            return {"engine": name}

        return fleet_pool.WarmPool(factory, size=size), made

    def test_heal_to_target_and_least_loaded_checkout(self):
        pool, made = self._pool(size=2)
        assert pool.heal() == ["resident-1", "resident-2"]
        a = pool.acquire()
        b = pool.acquire()
        assert {a.name, b.name} == {"resident-1", "resident-2"}
        pool.release(a)
        pool.release(b)
        assert pool.summary()["ready"] == 2
        assert all(r["inflight"] == 0
                   for r in pool.summary()["residents"])

    def test_kill_isolates_inflight_and_heal_respawns(self):
        pool, made = self._pool(size=2)
        pool.heal()
        res = pool.acquire()  # inflight work on resident-1
        assert pool.kill(res.name)
        assert not pool.kill(res.name)  # already dead
        # the dead resident takes no new checkouts; its inflight work
        # keeps its own engine (no double-merge onto a replacement)
        other = pool.acquire()
        assert other.name != res.name
        assert res.state == "dead" and res.inflight == 1
        healed = pool.heal()
        assert healed == ["resident-3"]
        assert pool.summary()["ready"] == 2
        pool.release(res)
        pool.release(other)

    def test_retire_refuses_last_ready_resident(self):
        pool, _ = self._pool(size=1)
        pool.heal()
        assert pool.retire_one() is None
        pool.spawn()
        assert pool.retire_one() is not None
        assert pool.retire_one() is None

    def test_empty_pool_acquire_spawns(self):
        pool, made = self._pool(size=2)
        res = pool.acquire()
        assert made == ["resident-1"]
        assert res.inflight == 1
        pool.release(res)

    def test_autoscale_decisions_get_executed(self, monkeypatch):
        """up -> spawn, down -> retire, and the audit ring's execution
        field records it (the /internal/autoscale contract)."""
        pool, _ = self._pool(size=2)
        pool.heal()
        reg = SliceRegistry()
        reg.register(SliceInfo("s0", max_replicas=3))
        p95 = [10.0]
        eng = AutoscaleEngine(reg, quantile_source=lambda: p95[0],
                              up_p95_s=5.0, down_p95_s=0.5,
                              cooldown_s=0.0)
        pool.attach_autoscale(eng)
        (up,) = eng.decide()
        assert up.direction == "up"
        assert pool.summary()["ready"] == 3
        p95[0] = 0.1
        (down,) = eng.decide()
        assert down.direction == "down"
        assert pool.summary()["ready"] == 2
        outcomes = [(e["direction"], e["execution"]["outcome"])
                    for e in eng.audit()["decisions"]]
        assert outcomes == [("up", "executed"), ("down", "executed")]

    def test_autoscale_cooldown_reports_failed(self):
        pool, _ = self._pool(size=2)
        pool.cooldown_s = 3600.0
        pool.heal()
        reg = SliceRegistry()
        reg.register(SliceInfo("s0", max_replicas=3))
        eng = AutoscaleEngine(reg, quantile_source=lambda: 10.0,
                              up_p95_s=5.0, down_p95_s=0.5,
                              cooldown_s=0.0)
        pool.attach_autoscale(eng)
        eng.decide()  # first execution consumes the cooldown window
        eng.decide()
        entries = eng.audit()["decisions"]
        assert entries[0]["execution"]["outcome"] == "executed"
        assert entries[1]["execution"] == {
            "outcome": "failed", "detail": "cooldown",
            "executed_at": entries[1]["execution"]["executed_at"]}

    def test_module_level_active_pool(self):
        pool, _ = self._pool()
        fleet_pool.set_pool(pool)
        try:
            assert fleet_pool.get_pool() is pool
        finally:
            fleet_pool.set_pool(None)
        assert fleet_pool.get_pool() is None


class TestDispatcherCheckout:
    def test_checkout_routes_to_resident_and_restores(self, monkeypatch):
        from stable_diffusion_webui_distributed_tpu.serving.dispatcher \
            import ServingDispatcher

        pool = fleet_pool.WarmPool(lambda name: {"engine": name}, size=1)
        pool.heal()
        disp = ServingDispatcher(engine="primary", window=0.0, pool=pool)
        monkeypatch.setenv("SDTPU_POOL", "1")
        assert disp._engine() == "primary"
        with disp._checkout_engine() as eng:
            assert eng == {"engine": "resident-1"}
            assert disp._engine() is eng  # stage helpers follow the lease
        assert disp._engine() == "primary"
        assert pool.summary()["residents"][0]["inflight"] == 0

    def test_gate_off_checkout_is_primary(self):
        from stable_diffusion_webui_distributed_tpu.serving.dispatcher \
            import ServingDispatcher

        pool = fleet_pool.WarmPool(lambda name: {"engine": name}, size=1)
        disp = ServingDispatcher(engine="primary", window=0.0, pool=pool)
        with disp._checkout_engine() as eng:  # SDTPU_POOL unset
            assert eng == "primary"
        assert pool.summary()["spawns_total"] == 0

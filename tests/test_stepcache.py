"""Step-cache subsystem tests (pipeline/stepcache.py + the engine's
step-cache chunk variant).

Host-side policy tests (cadence bucketing, cutoff mapping, schedule
mirror, serving group key) are tier-1 fast; everything that compiles a
tiny pipeline is marked slow, like the other compiled-pipeline modules.

The correctness contract under test:

- cadence 1 + cutoff 0 (the default) routes to the UNCHANGED plain
  executable — outputs byte-identical, zero new compiles;
- cadence > 1 / cutoff > 0 changes pixels only within a bounded PSNR
  drift against the exact baseline;
- the levers add exactly ONE static compile-key bit, so a shape bucket
  holds at most two chunk executables and cadence/cutoff changes on a
  warm bucket never recompile;
- carry/cache donation is declared on the chunk executables and the
  uint8 decode input.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import quality
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.models.unet import (
    deep_cache_shape,
)
from stable_diffusion_webui_distributed_tpu.pipeline import stepcache
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.samplers import kdiffusion as kd
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (
    ServingDispatcher,
)
from stable_diffusion_webui_distributed_tpu.serving.metrics import METRICS

#: Documented quality floor for the bench/bench-tier cadence-3 + cutoff
#: configuration on the tiny families (measured ~24-26 dB; see PERF.md).
PSNR_FLOOR_DB = 20.0


class TestBucketCadence:
    def test_ladder_rounds_down(self):
        assert stepcache.bucket_cadence(1) == 1
        assert stepcache.bucket_cadence(2) == 2
        assert stepcache.bucket_cadence(3) == 3
        assert stepcache.bucket_cadence(5) == 4
        assert stepcache.bucket_cadence(7) == 6
        assert stepcache.bucket_cadence(100) == 8  # clamps to top rung

    def test_garbage_means_off(self):
        assert stepcache.bucket_cadence(None) == 1
        assert stepcache.bucket_cadence("junk") == 1
        assert stepcache.bucket_cadence(-3) == 1
        assert stepcache.bucket_cadence(0) == 1

    def test_every_rung_is_a_fixed_point(self):
        for rung in stepcache.CADENCE_LADDER:
            assert stepcache.bucket_cadence(rung) == rung


class TestCutoffStep:
    SIGMAS = [8.0, 4.0, 2.0, 1.0, 0.5, 0.0]  # 5 steps + final x0

    def test_disabled_never_fires(self):
        # cfg_stop == n means the in-graph i >= cfg_stop never triggers
        assert stepcache.cutoff_step(self.SIGMAS, 0.0) == 5
        assert stepcache.cutoff_step(self.SIGMAS, -1.0) == 5

    def test_mid_ladder(self):
        # steps whose sigma is below 1.2 (indices 3, 4) run cond-only
        assert stepcache.cutoff_step(self.SIGMAS, 1.2) == 3

    def test_above_sigma_max_truncates_everything(self):
        assert stepcache.cutoff_step(self.SIGMAS, 100.0) == 0

    def test_below_sigma_min_never_fires(self):
        assert stepcache.cutoff_step(self.SIGMAS, 0.1) == 5

    def test_monotone_in_threshold(self):
        stops = [stepcache.cutoff_step(self.SIGMAS, s)
                 for s in (0.1, 0.7, 1.5, 3.0, 6.0, 9.0)]
        assert stops == sorted(stops, reverse=True)


class TestResolve:
    def test_defaults_off(self, monkeypatch):
        monkeypatch.delenv("SDTPU_DEEPCACHE", raising=False)
        monkeypatch.delenv("SDTPU_CFG_CUTOFF", raising=False)
        sc = stepcache.resolve(None)
        assert sc == stepcache.StepCacheSpec(1, 0.0)
        assert not sc.active

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("SDTPU_DEEPCACHE", "3")
        monkeypatch.setenv("SDTPU_CFG_CUTOFF", "1.5")
        sc = stepcache.resolve(None)
        assert (sc.cadence, sc.cutoff_sigma) == (3, 1.5)
        assert sc.active

    def test_override_settings_win_and_bucket(self, monkeypatch):
        monkeypatch.setenv("SDTPU_DEEPCACHE", "2")
        p = GenerationPayload(prompt="x",
                              override_settings={"deepcache": 5,
                                                 "cfg_cutoff": "0.7"})
        sc = stepcache.resolve(p)
        assert sc.cadence == 4  # 5 rounds DOWN onto the ladder
        assert sc.cutoff_sigma == pytest.approx(0.7)

    def test_bad_override_values(self, monkeypatch):
        monkeypatch.delenv("SDTPU_DEEPCACHE", raising=False)
        p = GenerationPayload(prompt="x",
                              override_settings={"deepcache": "junk",
                                                 "cfg_cutoff": "junk"})
        sc = stepcache.resolve(p)
        assert sc == stepcache.StepCacheSpec(1, 0.0)


class TestPlanSchedule:
    def test_cadence_one_refreshes_every_step(self):
        c = stepcache.plan_schedule([(0, 4, True)], cadence=1, cfg_stop=4,
                                    evals_per_step=1, total_steps=4)
        assert c["refreshes"] == 4
        assert c["deep_full"] == 4
        assert c["reuse_full_evals"] == 4
        assert c["full_evals"] == c["deep_trunc"] == 0

    def test_second_order_sampler_skips_final_midpoint(self):
        # Heun: 2 evals per step except the final step (sigma_next == 0)
        c = stepcache.plan_schedule([(0, 4, True)], cadence=2, cfg_stop=4,
                                    evals_per_step=2, total_steps=4)
        assert c["reuse_full_evals"] == 2 + 2 + 2 + 1
        assert c["refreshes"] == 2  # i = 0, 2

    def test_uncached_chunk_invalidates(self):
        chunks = [(0, 2, True), (2, 2, False), (4, 2, True)]
        c = stepcache.plan_schedule(chunks, cadence=4, cfg_stop=6,
                                    evals_per_step=1, total_steps=6)
        # step 0 refreshes (fresh range), steps 2-3 run the plain
        # executable, step 4 refreshes AGAIN on cache re-entry
        assert c["refreshes"] == 2
        assert c["full_evals"] == 2
        assert c["reuse_full_evals"] == 4

    def test_truncation_split(self):
        c = stepcache.plan_schedule([(0, 4, True)], cadence=1, cfg_stop=2,
                                    evals_per_step=1, total_steps=4)
        assert c["deep_full"] == 2 and c["deep_trunc"] == 2
        assert c["reuse_full_evals"] == 2 and c["reuse_trunc_evals"] == 2


class TestServingGroupKey:
    """Coalesced requests share ONE denoise range, so the resolved
    step-cache knobs must be part of the dispatcher's group key."""

    def _key(self, **ov):
        p = GenerationPayload(prompt="k", steps=8, width=64, height=64,
                              override_settings=ov or {})
        return ServingDispatcher._group_key(None, p)

    def test_knobs_split_groups(self):
        base = self._key()
        assert self._key(deepcache=3) != base
        assert self._key(cfg_cutoff=1.0) != base
        assert self._key(deepcache=3) != self._key(deepcache=2)

    def test_bucketed_cadences_merge(self):
        # 5 and 4 land on the same ladder rung -> same group
        assert self._key(deepcache=5) == self._key(deepcache=4)


# -- compiled-pipeline tests (slow tier, like test_pipeline) ---------------


@pytest.fixture(scope="module")
def engine():
    return quality.make_engine(TINY, chunk_size=4)


def _payload(**kw):
    kw.setdefault("prompt", "a cow")
    kw.setdefault("steps", 8)
    kw.setdefault("width", 32)
    kw.setdefault("height", 32)
    kw.setdefault("batch_size", 2)
    kw.setdefault("seed", 42)
    return GenerationPayload(**kw)


@pytest.fixture(scope="module")
def baseline(engine):
    return engine.txt2img(_payload())


@pytest.mark.slow
class TestCacheCorrectness:
    def test_inactive_is_byte_identical_and_plain(self, engine, baseline):
        before = METRICS.compile_count("chunk")
        r = engine.txt2img(_payload(
            override_settings={"deepcache": 1, "cfg_cutoff": 0.0}))
        # default knobs route to the plain executable already compiled by
        # the baseline run: same bytes, zero new chunk compiles
        assert r.images == baseline.images
        assert METRICS.compile_count("chunk") == before

    def test_cadence_drift_is_bounded(self, engine, baseline):
        r = engine.txt2img(_payload(
            override_settings={"deepcache": 3, "cfg_cutoff": 2.0}))
        db = quality.mean_psnr(r.images, baseline.images)
        assert db < quality.IDENTICAL_DB  # the levers actually engaged
        assert db >= PSNR_FLOOR_DB
        assert quality.mean_ssim(r.images, baseline.images) >= 0.5

    def test_knob_changes_do_not_recompile(self, engine, baseline):
        # first cached run on this bucket mints exactly one extra
        # executable (the step-cache variant)...
        engine.txt2img(_payload(override_settings={"deepcache": 2}))
        before = METRICS.compile_count("chunk")
        # ...after which cadence and cutoff travel as traced data
        engine.txt2img(_payload(
            override_settings={"deepcache": 3, "cfg_cutoff": 1.0}))
        engine.txt2img(_payload(
            override_settings={"deepcache": 4, "cfg_cutoff": 2.5}))
        assert METRICS.compile_count("chunk") == before

    def test_at_most_two_executables_per_bucket(self, engine):
        buckets = {}
        with engine._cache_lock:
            for k in engine._cache:
                if k[0] != "chunk":
                    continue
                buckets.setdefault(k[:-1], set()).add(k[-1])
        assert buckets, "no chunk executables compiled?"
        for bucket, variants in buckets.items():
            assert len(variants) <= 2, (bucket, variants)
            assert variants <= {False, True}

    def test_interrupt_then_rerun_matches(self, engine, baseline):
        """An interrupted cached run must not poison later runs: the
        deep-feature cache lives in the chunk-loop scan state, and every
        fresh range enters INVALID (refresh on first step)."""
        from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
            GenerationState,
        )

        st = GenerationState()
        eng2 = quality.make_engine(TINY, chunk_size=2)
        eng2.state = st
        ov = {"deepcache": 8, "cfg_cutoff": 0.0}  # one refresh per range
        ref = eng2.txt2img(_payload(override_settings=ov))

        armed = {"on": True}
        st.add_listener(
            lambda prog: st.flag.interrupt() if armed["on"] else None)
        partial = eng2.txt2img(_payload(override_settings=ov))
        assert len(partial.images) == 2  # partial latents still decoded
        assert st.progress.sampling_step < 8

        armed["on"] = False
        again = eng2.txt2img(_payload(override_settings=ov))
        assert again.images == ref.images

    def test_flops_metrics_recorded_and_cut(self, engine):
        METRICS.clear()
        engine.txt2img(_payload())
        plain = METRICS.unet_flops_per_image()
        assert plain and plain > 0
        assert METRICS.unet_images == 2

        METRICS.clear()
        engine.txt2img(_payload(
            override_settings={"deepcache": 3, "cfg_cutoff": 2.0}))
        cached = METRICS.unet_flops_per_image()
        assert cached and cached < plain
        s = METRICS.summary()
        assert s["unet_flops_per_image"] == pytest.approx(cached)


@pytest.mark.slow
class TestDonationDeclared:
    """The chunk executables donate their carry (and cache) inputs and the
    uint8 decode donates its latent rows — asserted on the lowered HLO
    (`tf.aliasing_output` is how declared+usable donation surfaces)."""

    def _chunk_args(self, engine, batch=1, lat=4):
        ucfg = engine.family.unet
        x = jnp.zeros((batch, lat, lat, ucfg.in_channels), jnp.float32)
        carry = kd.init_carry(x)
        ctx = jnp.zeros((1, 77, ucfg.cross_attention_dim), jnp.float32)
        keys = jax.random.split(jax.random.key(0), batch)
        return x, carry, ctx, keys

    def test_plain_chunk_aliases_carry(self, engine):
        fn = engine._chunk_fn("Euler", 4, 32, 32, 1, 2, masked=False)
        x, carry, ctx, keys = self._chunk_args(engine)
        hlo = fn.lower(
            engine.params["unet"], carry, jnp.int32(0), ctx, ctx,
            jnp.float32(7.0), keys, None, None, jnp.float32(0),
            jnp.float32(0), (), jnp.float32(0)).as_text()
        assert "tf.aliasing_output" in hlo

    def test_stepcache_chunk_aliases_carry_and_cache(self, engine):
        fn = engine._chunk_fn("Euler", 4, 32, 32, 1, 2, masked=False,
                              step_cache=True)
        x, carry, ctx, keys = self._chunk_args(engine)
        cache = jnp.zeros(deep_cache_shape(engine.family.unet, 2, 4, 4),
                          jnp.float32)
        hlo = fn.lower(
            engine.params["unet"], carry, cache, jnp.asarray(False),
            jnp.int32(0), ctx, ctx, jnp.float32(7.0), keys, None, None,
            jnp.float32(0), jnp.float32(0), jnp.float32(0),
            jnp.int32(3), jnp.int32(2)).as_text()
        assert hlo.count("tf.aliasing_output") >= 2  # carry.x AND cache

    def test_decode_u8_declares_unusable_donation(self, engine):
        # f32 latents can never alias the u8 output: the declaration must
        # still be present (JAX tells us via the donated-buffers warning;
        # the dispatch site in _queue_decoded suppresses exactly this)
        fn = engine._decode_u8_fn(32, 32, 1)
        lat = jnp.zeros((1, 4, 4, 4), jnp.float32)
        with pytest.warns(UserWarning,
                          match="donated buffers were not usable"):
            fn.lower(engine.params["vae"], lat).compile()

"""End-to-end pipeline tests on the tiny families (CPU, random weights).

Covers the minimum end-to-end slice of SURVEY.md §7 plus the seed-exact
range-split contract that replaces the reference's per-worker seed offsets
(/root/reference/scripts/distributed.py:297-305)."""

import base64
import io

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.models.configs import TINY, TINY_XL
from stable_diffusion_webui_distributed_tpu.models.clip import CLIPTextModel
from stable_diffusion_webui_distributed_tpu.models.unet import UNet
from stable_diffusion_webui_distributed_tpu.models.vae import VAE
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
    b64png_to_array,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)


def init_params(family):
    k = jax.random.key(0)
    ids = jnp.zeros((1, 77), jnp.int32)
    te = CLIPTextModel(family.text_encoder).init(k, ids)["params"]
    te2 = (CLIPTextModel(family.text_encoder_2).init(k, ids)["params"]
           if family.text_encoder_2 else None)
    ctx_dim = family.unet.cross_attention_dim
    args = [jnp.zeros((2, 8, 8, family.unet.in_channels)), jnp.ones((2,)),
            jnp.zeros((2, 77, ctx_dim))]
    if family.unet.addition_embed_dim:
        args.append(jnp.zeros((2, family.unet.projection_input_dim)))
    un = UNet(family.unet).init(k, *args)["params"]
    vae = VAE(family.vae).init(k, jnp.zeros((1, 16, 16, 3)),
                               jax.random.key(1))["params"]
    return {"text_encoder": te, "text_encoder_2": te2,
            "unet": un, "vae": vae}


@pytest.fixture(scope="module")
def engine():
    return Engine(TINY, init_params(TINY), chunk_size=4,
                  state=GenerationState())


@pytest.fixture(scope="module")
def engine_xl():
    return Engine(TINY_XL, init_params(TINY_XL), chunk_size=4,
                  state=GenerationState())


def decode(b64):
    return b64png_to_array(b64)


class TestTxt2Img:
    def test_shapes_seeds_infotext(self, engine):
        p = GenerationPayload(prompt="a cow", steps=6, width=64, height=64,
                              batch_size=2, seed=42)
        r = engine.txt2img(p)
        assert len(r.images) == 2
        assert r.seeds == [42, 43]
        img = decode(r.images[0])
        assert img.shape == (64, 64, 3)
        assert "Seed: 42" in r.infotexts[0]
        assert "Sampler: Euler a" in r.infotexts[0]

    def test_deterministic(self, engine):
        p = GenerationPayload(prompt="x", steps=4, width=32, height=32, seed=9)
        a = engine.txt2img(p).images[0]
        b = engine.txt2img(p).images[0]
        assert a == b

    def test_range_split_seed_exact(self, engine):
        """Sub-ranges == same images of the full batch: the DP contract."""
        p = GenerationPayload(prompt="a cow", steps=4, width=32, height=32,
                              batch_size=3, seed=100)
        full = engine.txt2img(p)
        part0 = engine.generate_range(p, 0, 1)
        part12 = engine.generate_range(p, 1, 2)
        assert part0.images[0] == full.images[0]
        assert part12.images == full.images[1:]
        assert part12.seeds == full.seeds[1:]

    def test_cond_cache_reused_across_requests(self, engine, monkeypatch):
        """Second request with the same prompt skips text encoding entirely
        (webui's cached_c/uc); a LoRA change invalidates the cache."""
        p = GenerationPayload(prompt="cache me", steps=2, width=32,
                              height=32, seed=3)
        first = engine.txt2img(p)
        enc = engine._encode_fn()
        calls = []

        def counting(*args, **kw):
            calls.append(1)
            return enc(*args, **kw)

        monkeypatch.setattr(engine, "_encode_fn", lambda: counting)
        again = engine.txt2img(p)
        assert again.images == first.images
        assert calls == []  # both cond and uncond came from the cache
        engine._cond_epoch += 1  # what set_loras does on a merge
        engine.txt2img(p)
        assert calls  # stale epoch -> re-encoded

    def test_decode_microbatch_slices_match(self, engine, monkeypatch):
        """Forcing the decode pixel budget down to one image per dispatch
        must yield the same images and ordering as a single-dispatch
        decode (SDXL-scale scratch bounding, engine._queue_decoded)."""
        p = GenerationPayload(prompt="mb", steps=3, width=32, height=32,
                              batch_size=3, seed=77)
        whole = engine.txt2img(p)
        monkeypatch.setenv("SDTPU_DECODE_PIXELS", str(32 * 32))
        sliced = engine.txt2img(p)
        assert sliced.images == whole.images
        assert sliced.seeds == whole.seeds

    def test_remainder_group_pad_and_drop(self, engine):
        """7 images at batch_size 2: the final odd group reuses the
        compiled 2-batch executable (pad-and-drop) and must produce the
        same images as a clean run."""
        p = GenerationPayload(prompt="pad", steps=3, width=32, height=32,
                              batch_size=2, n_iter=4, seed=60)
        full = engine.txt2img(p)  # 8 images, seeds 60..67
        p7 = p.model_copy()
        r7 = engine.generate_range(p7, 0, 7)
        assert len(r7.images) == 7
        assert r7.images == full.images[:7]
        assert r7.seeds == full.seeds[:7]

    def test_flash_attention_engine_end_to_end(self):
        """The engine with the Pallas flash-attention policy must reproduce
        the XLA-attention engine's output (interpret mode on CPU)."""
        from stable_diffusion_webui_distributed_tpu.runtime import dtypes

        params = init_params(TINY)
        p = GenerationPayload(prompt="f", steps=3, width=32, height=32,
                              seed=13)
        xla_eng = Engine(TINY, params, chunk_size=4, state=GenerationState())
        flash_eng = Engine(
            TINY, params, chunk_size=4, state=GenerationState(),
            policy=dtypes.Policy(compute_dtype=np.float32,
                                 attention_impl="flash"))
        a = xla_eng.txt2img(p)
        b = flash_eng.txt2img(p)
        ia = decode(a.images[0]).astype(np.int32)
        ib = decode(b.images[0]).astype(np.int32)
        assert np.abs(ia - ib).max() <= 1

    def test_n_iter(self, engine):
        p = GenerationPayload(prompt="y", steps=4, width=32, height=32,
                              batch_size=2, n_iter=2, seed=5)
        r = engine.txt2img(p)
        assert len(r.images) == 4
        assert r.seeds == [5, 6, 7, 8]

    def test_variation_seed_images_differ_but_share_base(self, engine):
        p0 = GenerationPayload(prompt="v", steps=4, width=32, height=32,
                               batch_size=2, seed=11, subseed=99,
                               subseed_strength=0.4)
        r = engine.txt2img(p0)
        assert r.images[0] != r.images[1]  # subseed advances per image
        assert r.seeds == [11, 11]         # base seed does not
        assert r.subseeds == [99, 100]


class TestImg2Img:
    def test_roundtrip(self, engine):
        src = GenerationPayload(prompt="s", steps=4, width=32, height=32,
                                seed=1)
        base = engine.txt2img(src).images[0]
        p = GenerationPayload(prompt="s", steps=6, width=32, height=32,
                              seed=2, init_images=[base],
                              denoising_strength=0.5)
        r = engine.img2img(p)
        assert decode(r.images[0]).shape == (32, 32, 3)

    def test_strength_zero_steps(self, engine):
        # strength ~0 -> almost no denoise steps; must not crash
        src = GenerationPayload(prompt="s", steps=4, width=32, height=32,
                                seed=1)
        base = engine.txt2img(src).images[0]
        p = GenerationPayload(prompt="s", steps=4, width=32, height=32,
                              seed=2, init_images=[base],
                              denoising_strength=0.1)
        r = engine.img2img(p)
        assert len(r.images) == 1

    def test_inpaint_mask(self, engine):
        src = GenerationPayload(prompt="s", steps=4, width=32, height=32,
                                seed=1)
        base = engine.txt2img(src).images[0]
        # mask: repaint left half only
        from PIL import Image

        m = np.zeros((32, 32, 3), np.uint8)
        m[:, :16] = 255
        buf = io.BytesIO()
        Image.fromarray(m).save(buf, format="PNG")
        mask_b64 = base64.b64encode(buf.getvalue()).decode()
        p = GenerationPayload(prompt="s", steps=6, width=32, height=32,
                              seed=3, init_images=[base], mask=mask_b64,
                              denoising_strength=0.9)
        r = engine.img2img(p)
        out = decode(r.images[0]).astype(np.int32)
        orig = decode(base).astype(np.int32)
        # unmasked right half stays close to the original
        right_diff = np.abs(out[:, 16:] - orig[:, 16:]).mean()
        left_diff = np.abs(out[:, :16] - orig[:, :16]).mean()
        assert right_diff < left_diff

    def test_hires_fix_output_size(self, engine):
        p = GenerationPayload(prompt="h", steps=4, width=32, height=32,
                              seed=4, enable_hr=True, hr_scale=2.0,
                              denoising_strength=0.7)
        r = engine.txt2img(p)
        assert decode(r.images[0]).shape == (64, 64, 3)

    def test_inpaint_fill_modes(self, engine):
        """webui inpainting_fill enum: original/latent-noise/latent-nothing/
        fill all produce valid, distinct repaints; the unmasked region stays
        pinned in every mode."""
        src = GenerationPayload(prompt="s", steps=4, width=32, height=32,
                                seed=1)
        base_img = engine.txt2img(src).images[0]
        from PIL import Image

        m = np.zeros((32, 32, 3), np.uint8)
        m[:, :16] = 255
        buf = io.BytesIO()
        Image.fromarray(m).save(buf, format="PNG")
        mask_b64 = base64.b64encode(buf.getvalue()).decode()

        outs = {}
        for fill in (1, 2, 3, 0):
            p = GenerationPayload(prompt="s", steps=6, width=32, height=32,
                                  seed=3, init_images=[base_img],
                                  mask=mask_b64, mask_blur=0,
                                  denoising_strength=0.9,
                                  inpainting_fill=fill)
            r = engine.img2img(p)
            outs[fill] = decode(r.images[0]).astype(np.int32)
            orig = decode(base_img).astype(np.int32)
            # pinned (right) side must move less than the repainted left
            right_diff = np.abs(outs[fill][:, 20:] - orig[:, 20:]).mean()
            left_diff = np.abs(outs[fill][:, :12] - orig[:, :12]).mean()
            assert right_diff < left_diff, (fill, right_diff, left_diff)
        assert not np.array_equal(outs[1], outs[3])  # nothing != original
        assert not np.array_equal(outs[1], outs[2])  # noise != original

    def test_infotext_round_trip(self):
        from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
            build_infotext, parse_infotext,
        )

        p = GenerationPayload(
            prompt="a (red:1.3) cow <lora:style:0.8>\nSteps: 3 of the "
                   "ritual\nsecond line",
            negative_prompt="ugly, blurry\nlowres second line",
            steps=25, width=640, height=512, seed=1234,
            sampler_name="DPM++ 2M Karras", cfg_scale=5.5,
            subseed=99, subseed_strength=0.4)
        text = build_infotext(p, p.seed, p.subseed, "model-x")
        back = parse_infotext(text)
        assert back.prompt == p.prompt
        assert back.negative_prompt == p.negative_prompt
        assert (back.steps, back.width, back.height) == (25, 640, 512)
        assert back.sampler_name == "DPM++ 2M Karras"
        assert back.cfg_scale == 5.5
        assert (back.seed, back.subseed) == (1234, 99)
        assert back.subseed_strength == 0.4

    def test_infotext_round_trip_seed_resize_and_ensd(self):
        from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
            build_infotext, parse_infotext,
        )

        p = GenerationPayload(
            prompt="cow", steps=10, seed=5,
            seed_resize_from_w=1024, seed_resize_from_h=768,
            override_settings={"eta_noise_seed_delta": 31337})
        back = parse_infotext(build_infotext(p, 5, 0, "m"))
        assert (back.seed_resize_from_w, back.seed_resize_from_h) == \
            (1024, 768)
        assert back.override_settings["eta_noise_seed_delta"] == 31337

    def test_seed_resize_and_ensd_change_output_deterministically(
            self, engine):
        base = dict(prompt="s", steps=3, width=32, height=32, seed=11)
        plain = engine.txt2img(GenerationPayload(**base))
        resized = engine.txt2img(GenerationPayload(
            **base, seed_resize_from_w=16, seed_resize_from_h=16))
        assert resized.images[0] != plain.images[0]
        again = engine.txt2img(GenerationPayload(
            **base, seed_resize_from_w=16, seed_resize_from_h=16))
        assert again.images[0] == resized.images[0]
        # ENSD shifts the ancestral sampler noise (Euler a default)
        shifted = engine.txt2img(GenerationPayload(
            **base, override_settings={"eta_noise_seed_delta": 31337}))
        assert shifted.images[0] != plain.images[0]

    def test_prompts_from_file_script(self, engine):
        from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
            apply_scripts,
        )

        p = GenerationPayload(
            prompt="ignored", steps=3, width=32, height=32, seed=40,
            script_name="Prompts from file or textbox",
            script_args=[True, False, "# comment\na cow\n\na dog\n"])
        expanded = apply_scripts(p)
        assert expanded.all_prompts == ["a cow", "a dog"]
        assert expanded.batch_size == 2 and expanded.group_size == 1
        assert not expanded.same_seed  # checkbox_iterate ON advances seeds
        r = engine.txt2img(p)
        assert len(r.images) == 2
        assert r.prompts == ["a cow", "a dog"]
        assert r.seeds == [40, 41]
        # line i reproduces a plain generation of that prompt at seed+i
        plain = engine.txt2img(GenerationPayload(
            prompt="a dog", steps=3, width=32, height=32, seed=41))
        assert r.images[1] == plain.images[0]

        # default (checkbox_iterate off): webui runs every line at the
        # request seed
        p2 = GenerationPayload(
            prompt="x", steps=3, width=32, height=32, seed=40,
            script_name="Prompts from file or textbox",
            script_args=[False, False, "a cow\na dog"])
        r2 = engine.txt2img(p2)
        assert r2.seeds == [40, 40]

    def test_prompt_matrix_expansion_order(self):
        from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
            expand_prompt_matrix,
        )

        got = expand_prompt_matrix("a cow|red|blue")
        # binary-counter order: bit j of index i selects option j (webui
        # scripts/prompt_matrix.py semantics)
        assert got == ["a cow", "a cow, red", "a cow, blue",
                       "a cow, red, blue"]

    def test_prompt_matrix_end_to_end(self, engine):
        from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
            apply_scripts,
        )

        p = GenerationPayload(prompt="a cow|red", steps=3, width=32,
                              height=32, seed=21,
                              script_name="Prompt Matrix")
        expanded = apply_scripts(p)
        assert expanded.batch_size == 2 and expanded.same_seed
        # the user's original batch_size caps the compiled dispatch group
        assert expanded.group_size == 1
        r = engine.txt2img(p)
        assert len(r.images) == 2
        assert r.prompts == ["a cow", "a cow, red"]
        assert r.seeds == [21, 21]  # fixed seed across the matrix
        assert r.images[0] != r.images[1]  # prompts actually condition
        assert "a cow, red" in r.infotexts[1]
        # matrix cell 0 == a plain single generation of the base prompt at
        # the same seed (same index-0 noise, same conditioning)
        plain = engine.txt2img(GenerationPayload(
            prompt="a cow", steps=3, width=32, height=32, seed=21))
        assert r.images[0] == plain.images[0]

    def test_all_prompts_range_contract(self, engine):
        # per-image prompts must survive the fan-out split: generating
        # [1, 3) standalone reproduces those rows of the full batch
        p = GenerationPayload(prompt="base", steps=3, width=32, height=32,
                              seed=9,
                              all_prompts=["base", "base b", "base c"],
                              batch_size=3)
        full = engine.txt2img(p)
        part = engine.generate_range(p, 1, 2)
        assert part.images == full.images[1:3]
        assert part.prompts == ["base b", "base c"]

    def test_context_padding_independent_of_slice(self, engine):
        # a short prompt grouped with a >1-chunk prompt gets a 2-chunk
        # context; the same image produced alone on another worker must
        # match bitwise, so the request-wide context length travels as
        # payload.context_chunks (engine.request_context_chunks)
        long_prompt = "a " + " ".join(f"word{i}" for i in range(90))
        p = GenerationPayload(prompt="base", steps=3, width=32, height=32,
                              seed=9, all_prompts=["short one", long_prompt],
                              batch_size=2, group_size=2)
        n = engine.request_context_chunks(p)
        assert n > 1  # the long prompt really spans multiple 77-token chunks
        full = engine.txt2img(p)

        # simulate the HTTP fan-out: the remote gets only ITS slice plus
        # the master's context_chunks (scheduler/worker.py slice logic)
        p_slice = p.model_copy()
        p_slice.all_prompts = ["short one"]
        p_slice.batch_size = 1
        p_slice.context_chunks = n
        part = engine.generate_range(p_slice, 0, 1)
        assert part.images[0] == full.images[0]

        # without the pin the slice pads to its own (shorter) context —
        # the bug this guards against would silently diverge
        p_bare = p_slice.model_copy()
        p_bare.context_chunks = None
        bare = engine.generate_range(p_bare, 0, 1)
        assert bare.images[0] != full.images[0]

    def test_hires_upscaler_variants(self, engine):
        base = dict(prompt="h", steps=3, width=32, height=32, seed=4,
                    enable_hr=True, hr_scale=2.0, denoising_strength=0.7)
        bilinear = engine.txt2img(GenerationPayload(**base))
        nearest = engine.txt2img(GenerationPayload(
            **base, hr_upscaler="Latent (nearest)"))
        assert nearest.images[0] != bilinear.images[0]
        # unknown model-based upscaler falls back to latent bilinear
        fallback = engine.txt2img(GenerationPayload(
            **base, hr_upscaler="R-ESRGAN 4x+"))
        assert fallback.images[0] == bilinear.images[0]


class TestXL:
    def test_txt2img(self, engine_xl):
        p = GenerationPayload(prompt="xl", steps=4, width=32, height=32,
                              seed=6)
        r = engine_xl.txt2img(p)
        assert decode(r.images[0]).shape == (32, 32, 3)


class TestVPrediction:
    def test_v_pred_runs_and_differs_from_epsilon(self):
        """Same weights under v-prediction vs epsilon parameterization must
        both generate, and differently (SD2.x 768-v support)."""
        from stable_diffusion_webui_distributed_tpu.models.configs import (
            TINY_V,
        )

        params = init_params(TINY)
        p = GenerationPayload(prompt="v", steps=4, width=32, height=32,
                              seed=3)
        eps_engine = Engine(TINY, params, chunk_size=4,
                            state=GenerationState())
        v_engine = Engine(TINY_V, params, chunk_size=4,
                          state=GenerationState())
        a = eps_engine.txt2img(p)
        b = v_engine.txt2img(p)
        assert a.images[0] != b.images[0]
        assert decode(b.images[0]).shape == (32, 32, 3)


class TestMeshEngine:
    def test_sharded_engine_matches_unsharded(self, engine, mesh8):
        """Engine on a dp=4,tp=2 mesh must reproduce the meshless images
        exactly — sharding is a placement decision, never a numerics one."""
        sharded = Engine(TINY, init_params(TINY), chunk_size=4,
                         state=GenerationState(), mesh=mesh8)
        p = GenerationPayload(prompt="mesh cow", steps=4, width=32,
                              height=32, batch_size=4, seed=21)
        a = engine.txt2img(p)
        b = sharded.txt2img(p)
        ia = np.stack([decode(x) for x in a.images]).astype(np.int32)
        ib = np.stack([decode(x) for x in b.images]).astype(np.int32)
        # identical placement-independent math; allow 1 LSB for reduction
        # order differences across device boundaries
        assert np.abs(ia - ib).max() <= 1

    def test_sp_mesh_ring_attention_matches(self, engine):
        """Engine on an sp=4 mesh routes latent self-attention through the
        ring — output must match the meshless run (sequence parallelism is
        a placement decision, not a numerics one)."""
        from stable_diffusion_webui_distributed_tpu.runtime.mesh import (
            build_mesh,
        )

        sharded = Engine(TINY, init_params(TINY), chunk_size=4,
                         state=GenerationState(), mesh=build_mesh("sp=4"))
        assert sharded.unet.attention_impl == "ring"
        p = GenerationPayload(prompt="ring cow", steps=3, width=32,
                              height=32, batch_size=2, seed=31)
        a = engine.txt2img(p)
        b = sharded.txt2img(p)
        ia = np.stack([decode(x) for x in a.images]).astype(np.int32)
        ib = np.stack([decode(x) for x in b.images]).astype(np.int32)
        assert np.abs(ia - ib).max() <= 1

    def test_sharded_engine_odd_batch_falls_back(self, engine, mesh8):
        sharded = Engine(TINY, init_params(TINY), chunk_size=4,
                         state=GenerationState(), mesh=mesh8)
        p = GenerationPayload(prompt="odd", steps=4, width=32, height=32,
                              batch_size=3, seed=22)
        r = sharded.txt2img(p)
        assert len(r.images) == 3


class TestRefiner:
    """SDXL base+refiner handoff (BASELINE config #2's two-model pass)."""

    @pytest.fixture(scope="class")
    def engines(self):
        from stable_diffusion_webui_distributed_tpu.models.configs import (
            TINY_REFINER, TINY_XL,
        )

        refiner = Engine(TINY_REFINER, init_params(TINY_REFINER),
                         chunk_size=4, state=GenerationState(),
                         model_name="tiny-ref")
        provider = lambda name: refiner if name == "tiny-ref" else None
        base = Engine(TINY_XL, init_params(TINY_XL), chunk_size=4,
                      state=GenerationState(), engine_provider=provider)
        return base, refiner

    def test_refiner_changes_output(self, engines):
        base_engine, _ = engines
        plain = base_engine.txt2img(GenerationPayload(
            prompt="c", steps=6, width=32, height=32, seed=9))
        refined = base_engine.txt2img(GenerationPayload(
            prompt="c", steps=6, width=32, height=32, seed=9,
            refiner_checkpoint="tiny-ref", refiner_switch_at=0.5))
        assert refined.images[0] != plain.images[0]

    def test_switch_at_one_is_base_only(self, engines):
        base_engine, _ = engines
        plain = base_engine.txt2img(GenerationPayload(
            prompt="c", steps=6, width=32, height=32, seed=9))
        same = base_engine.txt2img(GenerationPayload(
            prompt="c", steps=6, width=32, height=32, seed=9,
            refiner_checkpoint="tiny-ref", refiner_switch_at=1.0))
        assert same.images[0] == plain.images[0]

    def test_unknown_refiner_falls_back(self, engines):
        base_engine, _ = engines
        r = base_engine.txt2img(GenerationPayload(
            prompt="c", steps=4, width=32, height=32, seed=9,
            refiner_checkpoint="missing", refiner_switch_at=0.5))
        assert len(r.images) == 1


class TestDpmAdaptiveEngine:
    """DPM adaptive end-to-end: the engine routes it through the host-side
    PID loop (engine._denoise_adaptive), not the fixed-grid scan."""

    def test_txt2img_runs_and_is_deterministic(self, engine):
        p = GenerationPayload(prompt="adaptive cow", steps=8, width=32,
                              height=32, seed=21,
                              sampler_name="DPM adaptive")
        a = engine.txt2img(p)
        assert len(a.images) == 1
        assert "Sampler: DPM adaptive" in a.infotexts[0]
        b = engine.txt2img(p)
        assert a.images == b.images  # PID trajectory is deterministic
        # and it is genuinely a different algorithm than the fixed grid
        e = engine.txt2img(p.model_copy(update={"sampler_name": "Euler"}))
        assert e.images != a.images

    def test_img2img_runs(self, engine):
        base = GenerationPayload(prompt="seed image", steps=4, width=32,
                                 height=32, seed=5)
        init = engine.txt2img(base).images[0]
        p = GenerationPayload(prompt="adapted", steps=8, width=32, height=32,
                              seed=6, sampler_name="DPM adaptive",
                              init_images=[init], denoising_strength=0.6)
        r = engine.img2img(p)
        assert len(r.images) == 1

    def test_interrupt_between_attempts(self):
        st = GenerationState()
        eng = Engine(TINY, init_params(TINY), state=st)
        st.add_listener(lambda prog: st.flag.interrupt())
        p = GenerationPayload(prompt="i", steps=20, width=32, height=32,
                              seed=8, sampler_name="DPM adaptive")
        r = eng.txt2img(p)
        assert len(r.images) == 1  # partial result still decoded


def _host_mem_available_gb() -> float:
    """MemAvailable from /proc/meminfo in GiB; inf when unreadable (non-Linux
    hosts just run the test)."""
    try:
        with open("/proc/meminfo", "r", encoding="ascii") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) / (1024 * 1024)
    except (OSError, ValueError, IndexError):
        pass
    return float("inf")


class TestMixedFleetBitStability:
    """The same engine driven through a LocalBackend and through a real
    HTTP round-trip (this framework's server + HTTPBackend) must produce
    byte-identical images for EVERY sampler family — including DPM
    adaptive, whose host-side controller runs wherever the engine runs.
    (Divergence remains only vs legacy torch sdwui remotes; PARITY.md.)"""

    @pytest.mark.parametrize("sampler", ["Euler a", "DPM++ 2M Karras",
                                         "DPM adaptive"])
    def test_local_equals_http(self, engine, sampler, monkeypatch):
        from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
            HTTPBackend, LocalBackend,
        )
        from stable_diffusion_webui_distributed_tpu.server.api import (
            ApiServer,
        )

        if _host_mem_available_gb() < 8.0:
            pytest.skip("needs ~8 GiB host RAM for the HTTP round-trip")
        # ApiServer fronts a bare Engine with a ServingDispatcher whose
        # DEFAULT bucket ladder starts at 512x512 — padding this 32x32 tiny
        # request up 256x would allocate hundreds of GB on CPU. Pin a ladder
        # that matches the test shapes before the server is built.
        monkeypatch.setenv("SDTPU_BUCKET_LADDER", "32x32,64x64")
        monkeypatch.setenv("SDTPU_BATCH_LADDER", "1,2")

        p = GenerationPayload(prompt="fleet parity", steps=6, width=32,
                              height=32, batch_size=2, seed=77,
                              sampler_name=sampler)
        local = LocalBackend(engine).generate(p, 0, 2)
        srv = ApiServer(engine, state=engine.state,
                        host="127.0.0.1", port=0).start()
        try:
            remote = HTTPBackend("127.0.0.1", srv.port).generate(p, 0, 2)
        finally:
            srv.stop()
        assert remote.images == local.images
        assert remote.seeds == local.seeds


class TestInterrupt:
    def test_interrupt_stops_early(self):
        st = GenerationState()
        eng = Engine(TINY, init_params(TINY), chunk_size=1, state=st)
        # interrupt as soon as the first chunk reports progress
        st.add_listener(lambda prog: st.flag.interrupt())
        p = GenerationPayload(prompt="i", steps=12, width=32, height=32,
                              seed=8)
        r = eng.txt2img(p)
        # partial result is still decoded and returned (reference keeps
        # whatever images came back, distributed.py:158-169)
        assert len(r.images) == 1
        assert st.progress.sampling_step < 12


class TestDpmAdaptiveEdgeCases:
    def test_steps_1_denoises_full_range(self, engine):
        """steps=1 makes the ladder [sigma_max, 0]; the adaptive range must
        fall back to the schedule's own sigma_min (advisor r4) — webui's
        DPM adaptive ignores the slider, so steps=1 and steps=8 integrate
        the SAME [sigma_max, sigma_min] range and must match byte-exactly."""
        base = dict(prompt="one step", width=32, height=32, seed=31,
                    sampler_name="DPM adaptive")
        one = engine.txt2img(GenerationPayload(steps=1, **base))
        eight = engine.txt2img(GenerationPayload(steps=8, **base))
        assert one.images[0] == eight.images[0]

    def test_incomplete_trajectory_marked(self, engine, monkeypatch):
        """A run that hits the attempt backstop before sigma_min must be
        visible: warning + infotext marker (VERDICT r4 item 5)."""
        from stable_diffusion_webui_distributed_tpu.pipeline import (
            engine as engine_mod,
        )

        orig = engine_mod.kd.sample_dpm_adaptive

        def strangled(attempt_fn, x, sigma_max, sigma_min, **kw):
            # rtol so tight every step is rejected; tiny backstop
            kw.update(rtol=1e-12, atol=1e-14, max_attempts=3)
            return orig(attempt_fn, x, sigma_max, sigma_min, **kw)

        monkeypatch.setattr(engine_mod.kd, "sample_dpm_adaptive", strangled)
        r = engine.txt2img(GenerationPayload(
            prompt="stuck", steps=8, width=32, height=32, seed=32,
            sampler_name="DPM adaptive"))
        assert "DPM adaptive: incomplete" in r.infotexts[0]
        # and a normal run right after is NOT marked (per-request latch)
        monkeypatch.setattr(engine_mod.kd, "sample_dpm_adaptive", orig)
        ok = engine.txt2img(GenerationPayload(
            prompt="fine", steps=8, width=32, height=32, seed=33,
            sampler_name="DPM adaptive"))
        assert "incomplete" not in ok.infotexts[0]

"""Scenario engine (sim/): workload generation, chaos injection,
scoring, sweep ranking, the journal sink, and window replay.

Everything here is CPU-safe; the scheduler-tier scenarios run on stub
workers (no device) and the byte-identity pin uses the TINY engine
through the goldens mechanism. Covers:

- deterministic workload generation (same seed → byte-identical plan),
  the burst/diversity transforms, and loading a mix from a live
  snapshot, a snapshot file, and a JSONL sink file;
- the ``SDTPU_JOURNAL_SINK`` spill-on-evict contract: ring + sink stay
  a complete record, and both ``tools/replay.py`` and the workload
  loader read the sink;
- chaos: arm refused at SDTPU_SIM=0, hooks None by default, a scripted
  worker kill and a scripted stall both recovering to full delivery
  with zero double-merged images, fault_injected/fault_cleared in the
  journal, and ``sdtpu_sim_faults_total`` bumped;
- scorer arithmetic against hand-built records/events/ledger and the
  sweep ranking order;
- ``GET /internal/sim`` exact-schema snapshot;
- the SDTPU_SIM=0 default serving path hash-pinned via goldens.
"""

import json
import sys

import pytest

from stable_diffusion_webui_distributed_tpu import sim
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.obs import journal as obs_journal
from stable_diffusion_webui_distributed_tpu.obs import prometheus as obs_prom
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime.config import ConfigModel
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.scheduler import (
    worker as worker_mod,
)
from stable_diffusion_webui_distributed_tpu.scheduler import (
    world as world_mod,
)
from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
    StubBackend, StubBehavior, WorkerNode,
)
from stable_diffusion_webui_distributed_tpu.scheduler.world import World
from stable_diffusion_webui_distributed_tpu.server.api import ApiServer
from stable_diffusion_webui_distributed_tpu.serving import (
    dispatcher as dispatcher_mod,
)
from stable_diffusion_webui_distributed_tpu.serving.bucketer import (
    ShapeBucketer,
)
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (
    ServingDispatcher,
)
from stable_diffusion_webui_distributed_tpu.sim import (
    chaos as sim_chaos,
    score as sim_score,
    sweep as sim_sweep,
    workload as sim_workload,
)
from test_goldens import _check
from test_obsplane import call
from test_pipeline import init_params

sys.path.insert(0, "tools")

import replay  # noqa: E402  (tools/ on path)


def payload(**kw):
    defaults = dict(prompt="p", steps=20, width=512, height=512,
                    batch_size=4, seed=10)
    defaults.update(kw)
    return GenerationPayload(**defaults)


def stub_world():
    w = World(ConfigModel())
    w.add_worker(WorkerNode(
        "survivor", StubBackend(StubBehavior(seconds_per_image=0.001)),
        avg_ipm=2400.0))
    w.add_worker(WorkerNode(
        "victim", StubBackend(StubBehavior(seconds_per_image=0.001)),
        avg_ipm=2400.0))
    return w


@pytest.fixture()
def journal_on(monkeypatch):
    monkeypatch.setenv("SDTPU_JOURNAL", "1")
    obs_journal.JOURNAL.clear()
    yield obs_journal.JOURNAL
    obs_journal.JOURNAL.clear()


@pytest.fixture()
def sim_on(monkeypatch):
    monkeypatch.setenv("SDTPU_SIM", "1")
    yield
    sim_chaos.disarm()
    sim.clear_last_run()


# -- workload generator ------------------------------------------------------

class TestWorkload:
    def test_same_seed_identical_stream(self):
        mix = sim_workload.synthetic_mix(4)
        spec = sim_workload.WorkloadSpec(seed=7, count=20, rate_scale=3.0,
                                         diurnal_amplitude=0.5,
                                         burst_size=5)
        a = sim_workload.generate_plan(mix, spec)
        b = sim_workload.generate_plan(mix, spec)
        assert [r.dump() for r in a] == [r.dump() for r in b]
        assert sim_workload.plan_fingerprint(a) == \
            sim_workload.plan_fingerprint(b)
        other = sim_workload.generate_plan(
            mix, sim_workload.WorkloadSpec(seed=8, count=20,
                                           rate_scale=3.0,
                                           diurnal_amplitude=0.5,
                                           burst_size=5))
        assert sim_workload.plan_fingerprint(a) != \
            sim_workload.plan_fingerprint(other)

    def test_scaling_burst_and_diversity(self):
        mix = sim_workload.synthetic_mix(4)
        spec = sim_workload.WorkloadSpec(
            seed=3, count=12, burst_size=4, burst_at=0.5,
            shapes=[(64, 64), (64, 48)],
            precisions=["bf16", "int8"],
            tenants=["alice", "bob"], classes=["interactive", "batch"])
        plan = sim_workload.generate_plan(mix, spec)
        assert len(plan) == 16  # count + burst riders
        arrivals = [r.arrival_s for r in plan]
        assert arrivals == sorted(arrivals)
        # the burst is simultaneous: 4 extra requests share one arrival
        from collections import Counter
        top = Counter(arrivals).most_common(1)[0]
        assert top[1] >= 4
        assert {(r.payload.width, r.payload.height) for r in plan} <= \
            {(64, 64), (64, 48)}
        assert {r.payload.tenant for r in plan} <= \
            {"alice", "bob", "default"}
        # request ids are deterministic and unique
        rids = [r.request_id for r in plan]
        assert len(set(rids)) == len(rids)
        assert all(rid.startswith("sim-3-") for rid in rids)

    def test_mix_from_snapshot_events(self, journal_on):
        dump = payload(seed=42).model_dump()
        journal_on.emit("received", "r-1", payload=dump,
                        fingerprint=obs_journal.fingerprint(dump))
        journal_on.emit("completed", "r-1", seeds=[42])
        mix = sim_workload.base_mix(journal_on.snapshot()["events"])
        assert len(mix) == 1
        assert mix[0][0]["seed"] == 42
        assert mix[0][1] == 0.0  # arrivals normalized to t0


# -- journal sink ------------------------------------------------------------

class TestJournalSink:
    def test_spill_on_evict_completes_the_record(self, tmp_path,
                                                 monkeypatch):
        sink = tmp_path / "journal.jsonl"
        monkeypatch.setenv("SDTPU_JOURNAL", "1")
        monkeypatch.setenv("SDTPU_JOURNAL_SINK", str(sink))
        j = obs_journal.EventJournal(capacity=4)
        for i in range(10):
            j.emit("received", f"r-{i}", idx=i)
        # ring holds the newest 4; the sink holds the evicted 6
        assert len(j) == 4
        lines = sink.read_text().splitlines()
        assert len(lines) == 6
        spilled = [json.loads(ln) for ln in lines]
        assert sorted(e["seq"] for e in spilled) == [1, 2, 3, 4, 5, 6]
        assert j.sink_status() == {"path": str(sink), "spilled": 6,
                                   "bytes": sink.stat().st_size,
                                   "rotations": 0}
        # snapshot schema is unchanged by the sink
        assert set(j.snapshot()) == {"enabled", "capacity", "count",
                                     "total_emitted", "events"}

    def test_no_sink_no_spill(self, monkeypatch):
        monkeypatch.setenv("SDTPU_JOURNAL", "1")
        monkeypatch.delenv("SDTPU_JOURNAL_SINK", raising=False)
        j = obs_journal.EventJournal(capacity=2)
        for i in range(5):
            j.emit("received", f"r-{i}")
        assert j.sink_status() == {"path": "", "spilled": 0,
                                   "bytes": 0, "rotations": 0}

    def test_size_cap_rotates_to_dot1(self, tmp_path, monkeypatch):
        sink = tmp_path / "journal.jsonl"
        monkeypatch.setenv("SDTPU_JOURNAL", "1")
        monkeypatch.setenv("SDTPU_JOURNAL_SINK", str(sink))
        # cap ~= 7 event lines: the 10 evictions below rotate exactly
        # once, so the .1 + live pair still holds the full record
        monkeypatch.setenv("SDTPU_JOURNAL_SINK_MAX_MB", "0.00076")
        j = obs_journal.EventJournal(capacity=2)
        for i in range(12):
            j.emit("received", f"r-{i}", idx=i)
        st = j.sink_status()
        assert st["spilled"] == 10
        assert st["rotations"] == 1
        rotated = tmp_path / "journal.jsonl.1"
        assert rotated.exists()
        # single rollover: no .2 chain ever appears
        assert not (tmp_path / "journal.jsonl.2").exists()
        # the live file restarted under the cap; bytes tracks it exactly
        assert st["bytes"] == sink.stat().st_size
        assert 0 < st["bytes"] <= obs_journal.sink_max_bytes()
        # tools/replay loads the rotated pair as one contiguous stream
        snap = replay.load_snapshot(str(sink))
        assert [e["seq"] for e in snap["events"]] == list(range(1, 11))

    def test_rotated_pair_replays_all(self, tmp_path, monkeypatch):
        sink = tmp_path / "journal.jsonl"
        monkeypatch.setenv("SDTPU_JOURNAL", "1")
        monkeypatch.setenv("SDTPU_JOURNAL_SINK", str(sink))
        monkeypatch.setenv("SDTPU_JOURNAL_SINK_MAX_MB", "0.002")
        j = obs_journal.EventJournal(capacity=2)
        for i in range(6):
            dump = payload(seed=300 + i).model_dump()
            j.emit("received", f"rot-{i}", payload=dump,
                   fingerprint=obs_journal.fingerprint(dump))
            j.emit("completed", f"rot-{i}", seeds=[300 + i])
        assert j.sink_status()["rotations"] >= 1
        # replay --all reconstructs the retained requests across the
        # pair (repeated rotations drop the oldest chunks by design);
        # the .1 file's events come first, so seqs read contiguously
        snap = replay.load_snapshot(str(sink))
        seqs = [e["seq"] for e in snap["events"]]
        assert seqs == sorted(seqs) and len(seqs) >= 2
        rids = replay.request_ids(snap)
        assert rids
        replayable = 0
        for rid in rids:
            plan = replay.reconstruct(replay.events_for(snap, rid))
            if plan.outcome.get("status") == "completed" \
                    and plan.payload is not None:
                replayable += 1
        assert replayable >= 1

    def test_loaders_read_sink_and_snapshot(self, tmp_path, monkeypatch):
        sink = tmp_path / "sink.jsonl"
        snap_file = tmp_path / "snap.json"
        monkeypatch.setenv("SDTPU_JOURNAL", "1")
        monkeypatch.setenv("SDTPU_JOURNAL_SINK", str(sink))
        j = obs_journal.EventJournal(capacity=2)
        for i in range(4):
            dump = payload(seed=100 + i).model_dump()
            j.emit("received", f"r-{i}", payload=dump)
        snap_file.write_text(json.dumps(j.snapshot()))
        # tools/replay normalizes both shapes to a snapshot dict
        from_sink = replay.load_snapshot(str(sink))
        from_file = replay.load_snapshot(str(snap_file))
        assert [e["seq"] for e in from_sink["events"]] == [1, 2]
        assert [e["seq"] for e in from_file["events"]] == [3, 4]
        # the workload loader reads all three source kinds
        assert len(sim_workload.load_events(str(sink))) == 2
        assert len(sim_workload.load_events(str(snap_file))) == 2
        assert len(sim_workload.load_events(j.snapshot())) == 2
        # sink + ring together are the complete mix
        events = sim_workload.load_events(str(sink)) + \
            sim_workload.load_events(str(snap_file))
        assert len(sim_workload.base_mix(events)) == 4


# -- chaos injection ---------------------------------------------------------

class TestChaos:
    def test_hooks_none_by_default(self, monkeypatch):
        monkeypatch.delenv("SDTPU_SIM", raising=False)
        assert worker_mod.CHAOS_HOOK is None
        assert world_mod.CHAOS_HOOK is None
        assert dispatcher_mod.CHAOS_HOOK is None

    def test_arm_refused_when_disabled(self, monkeypatch):
        monkeypatch.delenv("SDTPU_SIM", raising=False)
        plan = sim_chaos.ChaosPlan([sim_chaos.Fault(kind="kill")])
        with pytest.raises(RuntimeError):
            sim_chaos.arm(plan)
        assert worker_mod.CHAOS_HOOK is None

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            sim_chaos.Fault(kind="meteor")

    def test_kill_recovers_with_zero_double_merge(self, sim_on,
                                                  journal_on):
        w = stub_world()
        plan = sim_chaos.ChaosPlan(
            [sim_chaos.Fault(kind="kill", worker="victim", at_request=1)],
            seed=11)
        faults0 = obs_prom.SIM_FAULT_COUNTER.total()
        sim_chaos.arm(plan)
        try:
            result = w.execute(payload(seed=50, steps=8,
                                       request_id="kill-0"))
        finally:
            sim_chaos.disarm()
        # full delivery, exact seed range, zero double-merge
        assert sorted(result.seeds) == [50, 51, 52, 53]
        assert len(result.images) == 4
        assert len(set(result.images)) == 4
        # the kill was delivered once, journaled, counted, and cleared
        st = plan.status()
        assert st["faults"][0]["injected"] == 1
        assert st["faults"][0]["cleared"] is True
        names = [e["event"] for e in journal_on.snapshot()["events"]]
        assert "fault_injected" in names and "fault_cleared" in names
        assert "requeued" in names  # the dead range moved to the survivor
        assert obs_prom.SIM_FAULT_COUNTER.total() - faults0 == 1
        # hooks are fully disarmed again
        assert worker_mod.CHAOS_HOOK is None
        assert world_mod.CHAOS_HOOK is None
        assert dispatcher_mod.CHAOS_HOOK is None

    def test_stall_recovers_via_watchdog(self, sim_on, journal_on,
                                         monkeypatch):
        monkeypatch.setenv("SDTPU_WATCHDOG_FACTOR", "2.0")
        w = stub_world()
        # the victim sleeps 1.2s before generating; its ETA at 2400 ipm
        # is 0.025 s/image, so the watchdog (factor 2) latches long
        # before the sleep ends and the range is requeued
        plan = sim_chaos.ChaosPlan(
            [sim_chaos.Fault(kind="stall", worker="victim", at_request=1,
                             duration_s=1.2)], seed=12)
        stalls0 = obs_prom.watchdog_stalls_total()
        sim_chaos.arm(plan)
        try:
            result = w.execute(payload(seed=60, steps=8,
                                       request_id="stall-0"))
        finally:
            sim_chaos.disarm()
        assert sorted(result.seeds) == [60, 61, 62, 63]
        assert len(result.images) == 4
        assert len(set(result.images)) == 4
        assert obs_prom.watchdog_stalls_total() > stalls0
        names = [e["event"] for e in journal_on.snapshot()["events"]]
        assert "fault_injected" in names

    def test_http_error_clears_after_count(self, sim_on):
        w = stub_world()
        plan = sim_chaos.ChaosPlan(
            [sim_chaos.Fault(kind="http_error", worker="victim",
                             at_request=1, count=1)], seed=13)
        sim_chaos.arm(plan)
        try:
            first = w.execute(payload(seed=70, steps=8))
            # fault exhausted: the next request sails through unharmed
            second = w.execute(payload(seed=80, steps=8))
        finally:
            sim_chaos.disarm()
        assert sorted(first.seeds) == [70, 71, 72, 73]
        assert sorted(second.seeds) == [80, 81, 82, 83]
        assert plan.status()["faults"][0]["remaining"] == 0


# -- scorer + sweep ----------------------------------------------------------

class TestScorer:
    def _records(self):
        return [
            {"class": "interactive", "status": "completed",
             "latency_s": 1.0, "expected": 1, "images": 1},
            {"class": "interactive", "status": "completed",
             "latency_s": 3.0, "expected": 1, "images": 1},
            {"class": "interactive", "status": "quota",
             "latency_s": 0.0, "expected": 1, "images": 0},
            {"class": "batch", "status": "completed",
             "latency_s": 5.0, "expected": 4, "images": 5},
            {"class": "batch", "status": "failed",
             "latency_s": 9.0, "expected": 4, "images": 0},
        ]

    def _events(self):
        return [
            {"event": "fault_injected", "attrs": {"kind": "kill"}},
            {"event": "fault_injected", "attrs": {"kind": "stall"}},
            {"event": "fault_cleared", "attrs": {"kind": "kill"}},
            {"event": "requeued", "attrs": {"worker": "survivor"}},
            {"event": "job_failed", "attrs": {}},
        ]

    def _ledger(self):
        return {
            "slo": [{"tenant": "alice", "class": "interactive",
                     "slo_s": 10.0, "total": 4, "met": 3,
                     "attainment": 0.75, "burn_rate": 5.0},
                    {"tenant": "bob", "class": "batch",
                     "slo_s": 40.0, "total": 2, "met": 2,
                     "attainment": 1.0, "burn_rate": 0.0}],
            "compiles": {"chunk": {"count": 2}, "decode": {"count": 1}},
            "groups": [{"dispatches": 3, "padding_ratio": 1.0},
                       {"dispatches": 1, "padding_ratio": 2.0}],
        }

    def test_scorecard_math(self):
        score = sim_score.score_run(
            self._records(), events=self._events(),
            ledger=self._ledger(),
            slo_s_by_class={"interactive": 2.0})
        assert score["requests"] == 5
        inter = score["classes"]["interactive"]
        assert inter["requests"] == 3
        assert inter["completed"] == 2 and inter["throttled"] == 1
        assert inter["p50_s"] == 1.0 and inter["p95_s"] == 3.0
        assert inter["slo_attainment"] == 0.5  # 1.0s met, 3.0s missed
        batch = score["classes"]["batch"]
        assert batch["failed"] == 1
        assert batch["slo_attainment"] is None  # no target given
        assert score["faults"] == {"kill": 1, "stall": 1}
        assert score["requeues"] == 1 and score["job_failures"] == 1
        # 1+1+0+4+0 delivered (capped at expected) of 11 expected; the
        # 5th batch image is a double merge
        assert score["expected_images"] == 11
        assert score["delivered_images"] == 6
        assert score["double_merged_images"] == 1
        assert score["requeue_recovery_rate"] == round(6 / 11, 6)
        assert score["worst_slo_burn"] == 5.0
        assert score["compiles"] == 3
        assert score["avg_padding_ratio"] == 1.25
        # the gauge latched the worst burn
        assert obs_prom.sim_slo_burn() == 5.0

    def test_clean_run_scores_full_recovery(self):
        records = [{"class": "interactive", "status": "completed",
                    "latency_s": 0.5, "expected": 2, "images": 2}]
        score = sim_score.score_run(records)
        assert score["requeue_recovery_rate"] == 1.0
        assert score["double_merged_images"] == 0
        assert score["faults"] == {}

    def test_ledger_metrics_flatten(self):
        score = sim_score.score_run(
            self._records(), events=self._events(),
            ledger=self._ledger(),
            slo_s_by_class={"interactive": 2.0})
        m = sim_score.ledger_metrics(score)
        assert m["scenario_p95_s"] == 5.0   # worst class p95
        assert m["slo_attainment"] == 0.5   # worst class attainment
        assert m["double_merged_images"] == 1
        assert m["faults_injected"] == 2
        assert m["requeue_recovery_rate"] == round(6 / 11, 6)

    def test_rank_prefers_attainment_then_p95_then_compiles(self):
        def fake(att, p95, compiles):
            return {"classes": {"interactive": {"slo_attainment": att,
                                                "p50_s": p95,
                                                "p95_s": p95}},
                    "compiles": compiles}
        out = sim_sweep.rank([
            {"name": "slow_but_meets", "score": fake(1.0, 4.0, 9)},
            {"name": "fast_but_misses", "score": fake(0.5, 1.0, 1)},
            {"name": "meets_faster", "score": fake(1.0, 2.0, 5)},
        ])
        assert [r["name"] for r in out["ranked"]] == \
            ["meets_faster", "slow_but_meets", "fast_but_misses"]
        assert out["recommendation"] == "meets_faster"
        # compiles break exact ties
        tied = sim_sweep.rank([
            {"name": "many_compiles", "score": fake(1.0, 2.0, 7)},
            {"name": "few_compiles", "score": fake(1.0, 2.0, 2)},
        ])
        assert tied["recommendation"] == "few_compiles"


# -- window replay (tools/replay.py) -----------------------------------------

class TestWindowReplay:
    def test_replays_all_requests_in_arrival_order(self, journal_on):
        w = stub_world()
        for i in range(3):
            w.execute(payload(seed=100 + 10 * i, steps=8,
                              request_id=f"win-{i}"))
        snapshot = journal_on.snapshot()
        rids = replay.request_ids(snapshot)
        assert rids == ["win-0", "win-1", "win-2"]
        # a fresh identical world replays every request byte-identically
        w2 = stub_world()

        def executor(dump):
            return w2.execute(GenerationPayload(**dump))

        report = replay.replay_window(snapshot, executor)
        assert report["requests"] == 3
        assert report["deterministic"] == 3
        assert report["diverged"] == 0 and report["skipped"] == 0

    def test_time_window_narrows(self, journal_on):
        w = stub_world()
        w.execute(payload(seed=1, steps=8, request_id="early"))
        w.execute(payload(seed=2, steps=8, request_id="late"))
        snapshot = journal_on.snapshot()
        events = snapshot["events"]
        late_t = min(e["t_mono"] for e in events
                     if e["request_id"] == "late")
        assert replay.request_ids(snapshot, t_min=late_t) == ["late"]
        assert replay.request_ids(snapshot, t_max=late_t - 1e-9) == \
            ["early"]


# -- /internal/sim + default-path pins ---------------------------------------

def make_world():
    w = World(ConfigModel())
    w.add_worker(WorkerNode("m", StubBackend(), master=True, avg_ipm=10.0))
    return w


@pytest.fixture(scope="class")
def server():
    srv = ApiServer(make_world(), state=GenerationState(),
                    host="127.0.0.1", port=0).start()
    yield srv
    srv.stop()


class TestSimEndpoint:
    def test_sim_endpoint_schema_snapshot(self, server, monkeypatch):
        monkeypatch.delenv("SDTPU_SIM", raising=False)
        out = call(server, "/internal/sim")
        assert set(out) == {"enabled", "sink", "chaos", "last_run"}
        assert out["enabled"] is False
        assert set(out["sink"]) == {"path", "spilled", "bytes",
                                    "rotations"}
        assert out["chaos"] == {"armed": False, "plan": None}
        assert out["last_run"] is None

    def test_sim_endpoint_reflects_state(self, server, monkeypatch):
        monkeypatch.setenv("SDTPU_SIM", "1")
        plan = sim_chaos.ChaosPlan(
            [sim_chaos.Fault(kind="slow", worker="w0", duration_s=0.1)])
        sim_chaos.arm(plan)
        sim.record_last_run("steady", {"requests": 3})
        try:
            out = call(server, "/internal/sim")
        finally:
            sim_chaos.disarm()
            sim.clear_last_run()
        assert out["enabled"] is True
        assert out["chaos"]["armed"] is True
        assert out["chaos"]["plan"]["faults"][0]["kind"] == "slow"
        assert out["last_run"]["name"] == "steady"


class TestDefaultPathPinned:
    def test_sim_off_serving_path_hash_pinned(self, monkeypatch):
        # SDTPU_SIM unset: the serving path must stay byte-identical
        # across sim/ refactors — frozen through the goldens mechanism
        monkeypatch.delenv("SDTPU_SIM", raising=False)
        monkeypatch.delenv("SDTPU_JOURNAL", raising=False)
        engine = Engine(TINY, init_params(TINY), chunk_size=4,
                        state=GenerationState())
        disp = ServingDispatcher(
            engine, bucketer=ShapeBucketer(shapes=[(32, 32)], batches=[1]),
            window=0.0)
        r = disp.submit(GenerationPayload(
            prompt="a golden scenario cow", width=32, height=32,
            steps=4, seed=4321, sampler_name="Euler a"))
        _check("serving/sim-off-default", r)

"""Scheduler tests: ETA model, state machine, five optimizer phases,
request fan-out/merge, elastic failure handling — all against stub backends
(SURVEY.md §4: the reference has no tests; this is the designed-from-scratch
strategy for its scheduling policy, /root/reference/scripts/spartan/
world.py:325-601, worker.py:36-41,176-286,719-758)."""

import pytest

from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime.config import (
    BenchmarkPayload, ConfigModel, WorkerModel,
)
from stable_diffusion_webui_distributed_tpu.scheduler import eta as eta_mod
from stable_diffusion_webui_distributed_tpu.scheduler.eta import (
    EtaCalibration, predict_eta, record_eta_error,
)
from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
    State, StubBackend, StubBehavior, WorkerNode,
)
from stable_diffusion_webui_distributed_tpu.scheduler.world import Job, World


def node(label, ipm, master=False, pixel_cap=0, behavior=None):
    return WorkerNode(label, StubBackend(behavior), master=master,
                      pixel_cap=pixel_cap, avg_ipm=ipm)


def payload(**kw):
    defaults = dict(prompt="p", steps=20, width=512, height=512,
                    batch_size=4, seed=10)
    defaults.update(kw)
    return GenerationPayload(**defaults)


class TestEta:
    def test_base_formula(self):
        cal = EtaCalibration(avg_ipm=6.0)  # 10 s per benchmark image
        p = payload(batch_size=2, steps=20, width=512, height=512)
        # 2 images at 6 ipm = 20 s; same steps/pixels as benchmark payload
        assert predict_eta(cal, p) == pytest.approx(20.0)

    def test_step_and_pixel_scaling(self):
        cal = EtaCalibration(avg_ipm=6.0)
        p = payload(batch_size=1, steps=40, width=1024, height=512)
        # 10 s * (40/20 steps) * (2x pixels) = 40 s
        assert predict_eta(cal, p) == pytest.approx(40.0)

    def test_sampler_table(self):
        cal = EtaCalibration(avg_ipm=6.0)
        base = predict_eta(cal, payload(batch_size=1))
        faster = predict_eta(
            cal, payload(batch_size=1, sampler_name="DPM++ 2M Karras"))
        slower = predict_eta(cal, payload(batch_size=1, sampler_name="Heun"))
        # +16.20% faster, -40.24% slower (reference worker.py:75-94)
        assert faster == pytest.approx(base * (1 - 0.1620))
        assert slower == pytest.approx(base * (1 + 0.4024))
        unknown = predict_eta(
            cal, payload(batch_size=1, sampler_name="Mystery Sampler"))
        assert unknown == pytest.approx(base)  # treated as Euler a

    def test_hires_pseudo_pass(self):
        cal = EtaCalibration(avg_ipm=6.0)
        plain = predict_eta(cal, payload(batch_size=1))
        hr = predict_eta(cal, payload(batch_size=1, enable_hr=True,
                                      hr_scale=2.0))
        # second pass at 4x pixels: base*(1 + 4) then *1 pixel ratio
        assert hr == pytest.approx(plain * 5.0)

    def test_mpe_correction_and_rejection(self):
        cal = EtaCalibration(avg_ipm=6.0)
        p = payload(batch_size=1)
        base = predict_eta(cal, p)
        record_eta_error(cal, predicted=12.0, actual=10.0)  # +20% error
        corrected = predict_eta(cal, p)
        assert corrected == pytest.approx(base * 0.8)
        # |error| >= 500% rejected (worker.py:483-490)
        record_eta_error(cal, predicted=100.0, actual=1.0)
        assert len(cal.eta_percent_error) == 1
        # window caps at 5
        for _ in range(10):
            record_eta_error(cal, predicted=11.0, actual=10.0)
        assert len(cal.eta_percent_error) == eta_mod.MPE_WINDOW

    def test_unbenchmarked_raises(self):
        with pytest.raises(ValueError):
            predict_eta(EtaCalibration(), payload())


class TestStateMachine:
    def test_happy_path(self):
        w = node("w", 10.0)
        assert w.set_state(State.WORKING)
        assert w.set_state(State.INTERRUPTED)
        assert w.set_state(State.WORKING)
        assert w.set_state(State.IDLE)

    def test_invalid_transition_refused(self):
        w = node("w", 10.0)
        assert not w.set_state(State.INTERRUPTED)  # IDLE -> INTERRUPTED
        assert w.state == State.IDLE

    def test_unavailable_invalidates_model_cache(self):
        w = node("w", 10.0)
        w.loaded_model = "m"
        w.loaded_vae = "v"
        w.set_state(State.UNAVAILABLE)
        assert w.loaded_model is None and w.loaded_vae is None
        # reconnect path: UNAVAILABLE -> IDLE forces re-sync
        assert w.set_state(State.IDLE)
        assert w.load_options("m2")
        assert w.backend.options["model"] == "m2"

    def test_disabled_refuses_unavailable(self):
        w = node("w", 10.0)
        w.state = State.DISABLED
        assert not w.set_state(State.UNAVAILABLE)
        assert w.state == State.DISABLED


class TestJobPixelCap:
    def test_uncapped(self):
        j = Job(node("w", 10.0, pixel_cap=0), 1)
        assert j.add_work(payload(), 100)

    def test_cap_blocks(self):
        # cap allows exactly 2 images at 512x512
        j = Job(node("w", 10.0, pixel_cap=2 * 512 * 512), 1)
        p = payload()
        assert j.add_work(p, 1)       # 2 images: at cap
        assert not j.add_work(p, 1)   # 3rd refused
        assert j.batch_size == 2


class TestOptimizer:
    def make_world(self, *nodes):
        w = World(ConfigModel())
        for n in nodes:
            w.add_worker(n)
        return w

    def test_equal_split_even(self):
        w = self.make_world(node("m", 10.0, master=True), node("a", 10.0))
        jobs = w.plan(payload(batch_size=4))
        assert [j.batch_size for j in jobs] == [2, 2]
        assert jobs[0].worker.master  # master leads the gallery
        assert [j.start_index for j in jobs] == [0, 2]

    def test_remainder_round_robin(self):
        w = self.make_world(node("m", 10.0, master=True), node("a", 10.0),
                            node("b", 10.0))
        jobs = w.plan(payload(batch_size=5))
        assert sum(j.batch_size for j in jobs) == 5
        sizes = sorted(j.batch_size for j in jobs)
        assert sizes == [1, 2, 2]

    def test_more_workers_than_images(self):
        w = self.make_world(node("m", 10.0, master=True), node("a", 10.0),
                            node("b", 10.0))
        jobs = w.plan(payload(batch_size=2))
        # reference world.py:506-510: trailing zero-share jobs dropped or
        # complementary; exactly 2 images land
        assert sum(j.batch_size for j in jobs if not j.complementary) == 2

    def test_slow_worker_deferred_and_redistributed(self):
        w = self.make_world(node("m", 60.0, master=True), node("slow", 1.0))
        w.complement_production = False
        # share=2 each; slow worker: 2 img at 1 ipm = 120 s vs 2 s -> stall
        jobs = w.plan(payload(batch_size=4))
        by_label = {j.worker.label: j for j in jobs}
        assert "slow" not in by_label  # deferred, no complementary work
        assert by_label["m"].batch_size == 4  # absorbed both deferred images

    def test_complementary_production(self):
        w = self.make_world(node("m", 60.0, master=True), node("slow", 6.0))
        w.job_timeout = 3
        w.complement_production = True
        # share=4: slow eta=40s vs fast ~4s -> defer; slack = 4+3 = 7s;
        # slow does 10s/image -> 0 bonus images... use slightly faster slow
        w2 = self.make_world(node("m", 60.0, master=True), node("s2", 30.0))
        w2.job_timeout = 3
        jobs = w2.plan(payload(batch_size=8))
        # s2: 4 img at 30ipm = 8s vs master 4s -> lag 4 > 3 -> deferred;
        # slack = master eta(absorbed batch) + 3; s2 2s/img -> bonus > 0
        comp = [j for j in jobs if j.complementary]
        assert comp and comp[0].worker.label == "s2"
        assert comp[0].batch_size >= 1

    def test_step_scaling(self):
        w = self.make_world(node("m", 60.0, master=True),
                            node("crawl", 0.5))
        w.job_timeout = 3
        w.step_scaling = True
        jobs = w.plan(payload(batch_size=4))
        comp = [j for j in jobs if j.complementary]
        # crawl: 120 s/image, slack ~7 s -> 0 bonus images; step scaling
        # gives it 1 image at reduced steps (reference world.py:547-557)
        assert comp and comp[0].step_override is not None
        assert 0 < comp[0].step_override < 20

    def test_unplaceable_request_raises(self):
        """Every cap below one image: plan() must raise, not quietly return
        an empty gallery."""
        w = self.make_world(node("m", 10.0, master=True,
                                 pixel_cap=100_000))  # < one 512x512 image
        with pytest.raises(RuntimeError, match="pixel caps"):
            w.plan(payload(batch_size=4))

    def test_slow_capped_worker_keeps_its_clamped_batch(self):
        """A slow worker whose cap limits it to a small batch is judged on
        THAT batch's stall, not the uncapped share (improvement the
        invariant sweep surfaced)."""
        w = self.make_world(
            node("m", 60.0, master=True),
            node("slowcap", 6.0, pixel_cap=1 * 512 * 512))
        w.job_timeout = 15
        w.complement_production = False
        jobs = w.plan(payload(batch_size=8))
        by_label = {j.worker.label: j for j in jobs}
        # share=4: uncapped stall would be 40s-4s >> 15s and defer it; the
        # clamped single image takes 10s vs fastest 4s -> stall 6s < 15s
        assert "slowcap" in by_label
        assert by_label["slowcap"].batch_size == 1
        assert by_label["m"].batch_size == 7

    def test_unavailable_worker_excluded(self):
        a, b = node("m", 10.0, master=True), node("b", 10.0)
        w = self.make_world(a, b)
        b.set_state(State.UNAVAILABLE)
        jobs = w.plan(payload(batch_size=4))
        assert len(jobs) == 1 and jobs[0].worker is a
        assert jobs[0].batch_size == 4


class TestOptimizerInvariants:
    """Property-style sweep: random fleets and workloads, invariants that
    must hold for EVERY plan (the optimizer is deterministic given speeds,
    payload, timeout, caps — SURVEY.md §4 test strategy)."""

    def test_random_scenarios(self):
        import random

        rng = random.Random(42)
        for trial in range(60):
            n_workers = rng.randint(1, 6)
            total = rng.randint(1, 24)
            w = World(ConfigModel())
            w.job_timeout = rng.choice([1, 3, 10])
            w.complement_production = rng.random() < 0.7
            w.step_scaling = rng.random() < 0.3
            for i in range(n_workers):
                cap = rng.choice([0, 0, 0, 2 * 512 * 512, 6 * 512 * 512])
                w.add_worker(node(f"w{i}", rng.uniform(0.5, 60.0),
                                  master=(i == 0), pixel_cap=cap))
            p = payload(batch_size=total, steps=rng.choice([10, 20, 40]))
            jobs = w.plan(p)
            ctx = f"trial {trial}: {[(j.worker.label, j.batch_size, j.complementary) for j in jobs]}"

            realtime_total = sum(j.batch_size for j in jobs
                                 if not j.complementary)
            # realtime jobs never overshoot the request
            assert realtime_total <= total, ctx
            # every surviving job carries work
            assert all(j.batch_size >= 1 for j in jobs), ctx
            # pixel caps respected by every realtime job's assignment
            for j in jobs:
                if j.worker.pixel_cap > 0 and not j.complementary:
                    assert j.batch_size * p.width * p.height \
                        <= j.worker.pixel_cap, ctx
            # ranges are contiguous and non-overlapping from 0
            starts = sorted((j.start_index, j.batch_size) for j in jobs)
            pos = 0
            for s, b in starts:
                assert s == pos, ctx
                pos += b
            # step overrides only appear with step scaling on, and reduced
            for j in jobs:
                if j.step_override is not None:
                    assert w.step_scaling and j.complementary, ctx
                    assert 0 < j.step_override < p.steps, ctx


class TestExecute:
    def test_merge_order_and_seed_continuity(self):
        w = World(ConfigModel())
        w.add_worker(node("m", 10.0, master=True))
        w.add_worker(node("a", 10.0))
        r = w.execute(payload(batch_size=4, seed=100))
        assert len(r.images) == 4
        # global order: images [0..4) in seed order regardless of worker
        assert r.seeds == [100, 101, 102, 103]
        assert r.images == [f"stub-image-{s}" for s in r.seeds]
        # worker attribution in infotext (distributed.py:343-349)
        assert "Worker Label: m" in r.infotexts[0]
        assert "Worker Label: a" in r.infotexts[-1]

    def test_failed_worker_requeued(self):
        w = World(ConfigModel())
        w.add_worker(node("m", 10.0, master=True))
        bad = node("bad", 10.0,
                   behavior=StubBehavior(fail_after_n_requests=0))
        w.add_worker(bad)
        r = w.execute(payload(batch_size=4, seed=100))
        # bad's 2 images re-queued on m: full gallery still delivered
        assert len(r.images) == 4
        assert r.seeds == [100, 101, 102, 103]
        assert bad.state == State.UNAVAILABLE

    def test_failed_range_split_across_capped_survivors(self):
        # bad is uncapped and ends up with 2 images; the survivors can only
        # take 1 image each (pixel cap), so recovery must SPLIT the range
        one_img = 512 * 512
        w = World(ConfigModel())
        bad = node("bad", 10.0, master=True,
                   behavior=StubBehavior(fail_generate=True))
        c1 = node("c1", 10.0, pixel_cap=one_img)
        c2 = node("c2", 10.0, pixel_cap=one_img)
        for n in (bad, c1, c2):
            w.add_worker(n)
        r = w.execute(payload(batch_size=4, seed=100))
        assert sorted(r.seeds) == [100, 101, 102, 103]
        assert len(r.images) == 4
        # each capped survivor served its original image + one recovered
        assert len(c1.backend.requests) == 2
        assert len(c2.backend.requests) == 2
        # no recovery request exceeded the survivor's cap
        for b in (c1.backend, c2.backend):
            assert all(req["count"] == 1 for req in b.requests)

    def test_second_failure_falls_through_to_next_survivor(self):
        w = World(ConfigModel())
        m = node("m", 10.0, master=True)
        f1 = node("f1", 10.0, behavior=StubBehavior(fail_generate=True))
        # f2 serves its first (planned) request, then fails the re-queue try
        f2 = node("f2", 12.0, behavior=StubBehavior(fail_after_n_requests=1))
        for n in (m, f1, f2):
            w.add_worker(n)
        r = w.execute(payload(batch_size=6, seed=100))
        assert sorted(r.seeds) == [100, 101, 102, 103, 104, 105]
        # f2 (fastest) was tried first for the recovery and failed; the
        # remainder landed on m
        assert len(f2.backend.requests) == 2
        assert f2.state == State.UNAVAILABLE

    def test_requeue_reapplies_step_override(self):
        w = World(ConfigModel())
        s = node("s", 10.0)
        w.add_worker(s)
        bad = node("bad", 10.0, behavior=StubBehavior(fail_generate=True))
        job = Job(bad, 2)
        job.start_index = 3
        job.step_override = 7
        recovered = w._requeue_failed(job, payload(steps=20))
        assert len(recovered) == 1 and recovered[0].worker is s
        req = s.backend.requests[-1]
        assert req["payload"].steps == 7
        assert (req["start"], req["count"]) == (3, 2)
        assert recovered[0].step_override == 7

    def test_http_backend_slices_all_prompts_and_pins_same_seed(self):
        # the wire fan-out: a remote gets ITS slice of all_prompts indexed
        # from 0, and same-seed (prompt matrix) batches keep the request
        # seed un-offset
        from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
            HTTPBackend,
        )

        captured = {}

        class FakeResp:
            status_code = 200

            def raise_for_status(self):
                pass

            def json(self):
                n = captured["body"]["batch_size"]
                return {"images": ["x"] * n, "info": {
                    "all_seeds": [0] * n, "all_subseeds": [0] * n,
                    "all_prompts": [""] * n, "infotexts": [""] * n}}

        backend = HTTPBackend("h", 1)
        backend.session.post = lambda url, json=None, timeout=0: (
            captured.update(body=json) or FakeResp())

        p = payload(batch_size=6, seed=100,
                    all_prompts=[f"p{i}" for i in range(6)], same_seed=True)
        backend.generate(p, 2, 3)
        assert captured["body"]["all_prompts"] == ["p2", "p3", "p4"]
        assert captured["body"]["seed"] == 100  # pinned, not offset
        # without same_seed the classic offset applies
        p2 = payload(batch_size=6, seed=100,
                     all_prompts=[f"p{i}" for i in range(6)])
        backend.generate(p2, 2, 3)
        assert captured["body"]["seed"] == 102
        assert captured["body"]["all_prompts"] == ["p2", "p3", "p4"]

    def test_self_looping_script_bypasses_distribution(self):
        # ADetailer-style scripts re-run img2img themselves; the request
        # must run whole on the master (reference distributed.py:207-212)
        w = World(ConfigModel())
        m = node("m", 10.0, master=True)
        a = node("a", 10.0)
        w.add_worker(m)
        w.add_worker(a)
        r = w.execute(payload(
            batch_size=4, seed=100,
            alwayson_scripts={"ADetailer": {"args": [{"enabled": True}]}}))
        assert len(r.images) == 4
        assert len(a.backend.requests) == 0  # never distributed
        assert len(m.backend.requests) == 1
        assert m.backend.requests[0]["count"] == 4
        assert r.worker_labels == ["m"] * 4

    def test_inflight_interrupt_aborts_remote_request(self):
        # While an HTTP-style request is in flight, the watchdog polls the
        # master's interrupt flag and fires backend.interrupt() — the
        # remote returns early with the images finished so far
        # (reference worker.py:440-448 mid-request propagation).
        import threading
        import time as time_mod

        from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
            GenerationState,
        )

        slow = node("slow", 10.0,
                    behavior=StubBehavior(seconds_per_image=0.4))
        state = GenerationState()
        slow.interrupt_state = state
        slow.interrupt_poll_s = 0.05

        t = threading.Timer(0.3, state.flag.interrupt)
        t.start()
        t0 = time_mod.monotonic()
        result = slow.request(payload(batch_size=8, seed=1), 0, 8)
        elapsed = time_mod.monotonic() - t0
        t.cancel()
        assert slow.backend.interrupted
        # aborted mid-flight: far fewer than 8 images, far sooner than 3.2s
        assert result is not None and len(result.images) < 8
        assert elapsed < 1.5

    def test_ping_revives_and_demotes(self):
        w = World(ConfigModel())
        good = node("good", 10.0)
        flaky = node("flaky", 10.0,
                     behavior=StubBehavior(fail_reachable=True))
        w.add_worker(good)
        w.add_worker(flaky)
        res = w.ping_workers()
        assert res == {"good": True, "flaky": False}
        assert flaky.state == State.UNAVAILABLE
        flaky.backend.behavior.fail_reachable = False
        res = w.ping_workers()
        assert res["flaky"] is True
        assert flaky.state == State.IDLE


class TestWorkerControl:
    def test_restart_all_skips_master_and_disabled(self):
        w = World(ConfigModel())
        m = node("m", 10.0, master=True)
        a = node("a", 10.0)
        d = node("d", 10.0)
        for n_ in (m, a, d):
            w.add_worker(n_)
        d.set_state(State.DISABLED)
        results = w.restart_all()
        assert results == {"a": True}
        assert a.backend.restarted and not d.backend.restarted
        assert a.state == State.UNAVAILABLE  # until the next ping revives
        # master untouched: LocalBackend-style restart is its own route
        assert m.state != State.UNAVAILABLE

    def test_restart_failure_reports_false(self):
        w = World(ConfigModel())
        bad = node("bad", 10.0,
                   behavior=StubBehavior(fail_reachable=True))
        w.add_worker(bad)
        assert w.restart_all() == {"bad": False}

    def test_user_script_runs_sync_file(self, tmp_path):
        # reference user_script_btn (ui.py:26-55): a sync* file under
        # <config dir>/user/, launched via its shebang
        w = World(ConfigModel(), config_path=str(tmp_path / "cfg.json"))
        assert w.run_user_script() is False  # no user/ dir yet

        user = tmp_path / "user"
        user.mkdir()
        marker = tmp_path / "ran.txt"
        script = user / "sync-models.sh"
        script.write_text(f"#!/bin/sh\necho ok > {marker}\n")
        assert w.run_user_script() is True
        assert marker.read_text().strip() == "ok"

        # a failing script reports False
        script.write_text("#!/bin/sh\nexit 3\n")
        assert w.run_user_script() is False

    def test_configure_worker_roundtrips_and_load_options_honors(self,
                                                                 tmp_path):
        path = str(tmp_path / "cfg.json")
        w = World(ConfigModel(), config_path=path)
        a = node("a", 10.0)
        w.add_worker(a)
        assert w.configure_worker("a", model_override="anime-v3",
                                  pixel_cap=4 * 512 * 512)
        assert not w.configure_worker("ghost")
        # persisted...
        from stable_diffusion_webui_distributed_tpu.runtime.config import (
            load_config,
        )

        cfg2 = load_config(path)
        w2 = World.from_config(
            cfg2, backend_factory=lambda label, wm: StubBackend())
        a2 = w2.get_worker("a")
        assert a2.model_override == "anime-v3"
        assert a2.pixel_cap == 4 * 512 * 512
        # ...and honored: model sync sends the pin, not the fleet model
        a2.load_options("fleet-model")
        assert a2.backend.options["model"] == "anime-v3"
        # clearing the pin restores fleet-model sync
        w2.config_path = None
        w2.configure_worker("a", model_override="")
        a2.load_options("fleet-model")
        assert a2.backend.options["model"] == "fleet-model"

    def test_add_remove_remote_worker_live(self, tmp_path):
        # the reference's Worker Config tab adds/removes workers on a
        # RUNNING fleet (ui.py:90-186); verify registry + persistence
        path = str(tmp_path / "cfg.json")
        w = World(ConfigModel(), config_path=path)
        master = node("local", 10.0)
        master.master = True
        w.add_worker(master)
        n = w.add_remote_worker("r1", "10.0.0.5", 7860, tls=True,
                                user="u", password="p", pixel_cap=99)
        assert w.get_worker("r1") is n
        assert n.backend.address == "10.0.0.5" and n.backend.tls
        with pytest.raises(ValueError):
            w.add_remote_worker("r1", "10.0.0.5", 7860)  # duplicate
        with pytest.raises(ValueError):
            w.add_remote_worker("r2", "", 7860)          # no address
        # persisted with credentials; survives a reload
        from stable_diffusion_webui_distributed_tpu.runtime.config import (
            load_config,
        )

        w2 = World.from_config(load_config(path))
        r1 = w2.get_worker("r1")
        assert r1 is not None and r1.pixel_cap == 99
        assert r1.backend.user == "u" and r1.backend.password == "p"
        # removal drops it from registry and config
        assert w.remove_worker("r1")
        assert w.get_worker("r1") is None
        assert not w.remove_worker("ghost")
        with pytest.raises(ValueError):
            w.remove_worker("local")  # master is never removable
        w3 = World.from_config(load_config(path))
        assert w3.get_worker("r1") is None

    def test_configure_worker_disable_enable(self):
        w = World(ConfigModel())
        a = node("a", 10.0)
        w.add_worker(a)
        w.configure_worker("a", disabled=True)
        assert a.state == State.DISABLED
        assert w.get_workers() == []
        w.configure_worker("a", disabled=False)
        assert a.state == State.IDLE

    def test_apply_settings(self):
        w = World(ConfigModel())
        applied = w.apply_settings({
            "job_timeout": 7, "step_scaling": True,
            "complement_production": False, "ignored_key": 1})
        assert applied == {"job_timeout": 7.0, "step_scaling": True,
                           "complement_production": False}
        assert w.job_timeout == 7.0 and w.step_scaling \
            and not w.complement_production


class TestConcurrency:
    """Race coverage for the shared World/worker state (SURVEY §5 notes the
    reference mutates cross-thread without locks; we exercise ours)."""

    def test_parallel_executes_and_sweeps(self):
        import threading

        w = World(ConfigModel())
        w.add_worker(node("m", 10.0, master=True))
        w.add_worker(node("a", 10.0))
        w.add_worker(node("b", 10.0))
        errors = []

        def do_execute(i):
            try:
                r = w.execute(payload(batch_size=3, seed=1000 + i * 10))
                assert len(r.images) == 3
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def do_sweep():
            try:
                for _ in range(5):
                    w.ping_workers()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=do_execute, args=(i,))
                   for i in range(4)]
        threads.append(threading.Thread(target=do_sweep))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

    def test_first_contact_memory_probe(self):
        w = node("m", 10.0)
        assert w.free_memory is None
        w.request(payload(batch_size=1, seed=1), 0, 1)
        assert w.free_memory is not None  # probed exactly once
        probed = w.free_memory
        w.request(payload(batch_size=1, seed=2), 0, 1)
        assert w.free_memory == probed


class TestBenchmark:
    def test_stub_benchmark_records_ipm(self):
        w = node("w", None)
        assert not w.cal.benchmarked
        ipm = w.benchmark()
        assert ipm and ipm > 0
        assert len(w.backend.requests) == 5  # 2 warmup + 3 recorded

    def test_benchmark_cached_unless_rebenchmark(self):
        w = node("w", 12.0)
        assert w.benchmark() == 12.0
        assert len(w.backend.requests) == 0  # cached, no generation

    def test_world_roundtrip_via_config(self, tmp_path):
        w = World(ConfigModel(), str(tmp_path / "cfg.json"))
        n = node("m", 42.0, master=True)
        n.cal.eta_percent_error = [1.0, -2.0]
        w.add_worker(n)
        w.save_config()
        cfg = ConfigModel(**w.cfg.model_dump())
        w2 = World.from_config(
            cfg, backend_factory=lambda label, wm: StubBackend())
        m = w2.get_worker("m")
        assert m.cal.avg_ipm == 42.0
        assert m.cal.eta_percent_error == [1.0, -2.0]
        assert m.master

    def test_master_not_resurrected_as_http(self):
        """A persisted master entry must NOT come back as an HTTP worker
        dialing our own port; its calibration is still readable."""
        cfg = ConfigModel(workers=[
            {"master": WorkerModel(master=True, avg_ipm=30.0)},
            {"r1": WorkerModel(address="10.0.0.9", port=7861, avg_ipm=5.0)},
        ])
        w = World.from_config(cfg)
        assert w.get_worker("master") is None
        assert w.get_worker("r1") is not None
        assert w.master_calibration().avg_ipm == 30.0

    def test_save_config_keeps_credentials(self, tmp_path):
        from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
            HTTPBackend,
        )

        w = World(ConfigModel(), str(tmp_path / "cfg.json"))
        backend = HTTPBackend("10.0.0.2", 7861, user="u", password="secret")
        w.add_worker(WorkerNode("r", backend, avg_ipm=8.0))
        w.save_config()
        wm = w.cfg.workers[0]["r"]
        assert (wm.user, wm.password) == ("u", "secret")
        assert (wm.address, wm.port) == ("10.0.0.2", 7861)

    def test_model_synced_to_remotes_before_fanout(self):
        """The reference pushes the checkpoint with each request when the
        worker's cache differs (worker.py:342-343); execute() must do the
        same for non-master backends."""
        w = World(ConfigModel())
        w.current_model = "modelB"
        w.add_worker(node("m", 10.0, master=True))
        remote = node("r", 10.0)
        w.add_worker(remote)
        r = w.execute(payload(batch_size=4, seed=1))
        assert len(r.images) == 4
        assert remote.backend.options == {"model": "modelB", "vae": ""}
        assert remote.loaded_model == "modelB"
        # second request: cache hit, no re-send
        remote.backend.options = {}
        w.execute(payload(batch_size=2, seed=2))
        assert remote.backend.options == {}

    def test_save_config_preserves_persisted_master(self, tmp_path):
        """ping/status Worlds have no master worker; saving must not erase
        the master's persisted calibration."""
        cfg = ConfigModel(workers=[
            {"master": WorkerModel(master=True, avg_ipm=33.0)},
            {"r1": WorkerModel(address="10.0.0.9", avg_ipm=5.0)},
        ])
        w = World.from_config(cfg, backend_factory=None)
        w.save_config()
        masters = [e for e in w.cfg.workers if "master" in e]
        assert masters and masters[0]["master"].avg_ipm == 33.0

    def test_script_args_filtered_per_worker(self):
        """Unsupported alwayson scripts are stripped per backend
        (reference worker.py:375-404)."""
        from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
            StubBehavior,
        )

        w = World(ConfigModel())
        caps = node("caps", 10.0,
                    behavior=StubBehavior(supported_scripts=("controlnet",)))
        bare = node("bare", 10.0,
                    behavior=StubBehavior(supported_scripts=()))
        w.add_worker(caps)
        w.add_worker(bare)
        for n_ in (caps, bare):
            n_.reachable()  # populates supported_scripts
        p = payload(batch_size=4, seed=1)
        # "dynamic prompts" is a plain per-request script (NOT one of the
        # self-looping set that bypasses distribution, SELF_LOOPING_SCRIPTS)
        p.alwayson_scripts = {"controlnet": {"args": [{"enabled": True}]},
                              "dynamic prompts": {"args": []}}
        w.execute(p)
        sent_caps = caps.backend.requests[-1]["payload"].alwayson_scripts
        sent_bare = bare.backend.requests[-1]["payload"].alwayson_scripts
        assert set(sent_caps) == {"controlnet"}  # unsupported stripped
        assert sent_bare == {}

    def test_thin_client_mode_excludes_master(self):
        """Thin-client: the master coordinates but generates nothing
        (reference world.py:411-412; bypass at 564-594)."""
        w = World(ConfigModel())
        master = node("m", 60.0, master=True)
        w.add_worker(master)
        w.add_worker(node("a", 10.0))
        w.thin_client_mode = True
        r = w.execute(payload(batch_size=4, seed=50))
        assert len(r.images) == 4
        assert master.backend.requests == []  # no local generation
        assert all(l == "a" for l in r.worker_labels)

    def test_execute_resolves_random_seed_once(self):
        w = World(ConfigModel())
        w.add_worker(node("m", 10.0, master=True))
        w.add_worker(node("a", 10.0))
        r = w.execute(payload(batch_size=4, seed=-1))
        # one coherent contiguous range across both workers
        base = r.seeds[0]
        assert base != -1
        assert r.seeds == [base, base + 1, base + 2, base + 3]


class TestAdaptiveNoSplit:
    """DPM adaptive's batch-global PID error norm makes pixels depend on
    batch composition, so adaptive requests must never split across
    workers (PARITY.md contract exception; advisor r4 medium finding)."""

    def test_whole_request_on_fastest(self):
        w = World(ConfigModel())
        w.add_worker(node("m", 10.0, master=True))
        w.add_worker(node("fast", 30.0))
        jobs = w.plan(payload(batch_size=4, sampler_name="DPM adaptive"))
        assert len(jobs) == 1
        assert jobs[0].worker.label == "fast"
        assert jobs[0].batch_size == 4
        assert jobs[0].start_index == 0

    def test_pixel_cap_picks_fitting_backend(self):
        w = World(ConfigModel())
        # fastest cannot fit 4 x 512x512; slower uncapped one can
        w.add_worker(node("capped", 30.0, pixel_cap=2 * 512 * 512))
        w.add_worker(node("roomy", 10.0, master=True))
        jobs = w.plan(payload(batch_size=4, sampler_name="DPM adaptive"))
        assert len(jobs) == 1
        assert jobs[0].worker.label == "roomy"

    def test_falls_back_to_split_when_nothing_fits(self):
        w = World(ConfigModel())
        w.add_worker(node("a", 10.0, master=True, pixel_cap=2 * 512 * 512))
        w.add_worker(node("b", 10.0, pixel_cap=2 * 512 * 512))
        jobs = w.plan(payload(batch_size=4, sampler_name="DPM adaptive"))
        assert sum(j.batch_size for j in jobs) == 4
        assert len(jobs) == 2  # documented degraded mode, loudly logged

    def test_fixed_grid_sampler_still_splits(self):
        w = World(ConfigModel())
        w.add_worker(node("m", 10.0, master=True))
        w.add_worker(node("a", 10.0))
        jobs = w.plan(payload(batch_size=4, sampler_name="Euler a"))
        assert len(jobs) == 2

    def test_label_tie_break_deterministic(self):
        # equal avg_ipm: lowest label wins, every time — plan output must
        # not depend on worker registration order or dict iteration
        w = World(ConfigModel())
        w.add_worker(node("zeta", 10.0, master=True))
        w.add_worker(node("alpha", 10.0))
        for _ in range(3):
            jobs = w.plan(payload(batch_size=4, sampler_name="DPM adaptive"))
            assert [j.worker.label for j in jobs] == ["alpha"]

    def test_all_fitting_backends_stalled_still_no_split(self):
        # the only backend that FITS the request stalls badly vs the
        # (capped) fastest; a slow whole-request run still beats splitting,
        # which would change the adaptive trajectory and the pixels
        w = World(ConfigModel())
        w.add_worker(node("fast-capped", 30.0, master=True,
                          pixel_cap=2 * 512 * 512))
        w.add_worker(node("slow-roomy", 1.0))
        jobs = w.plan(payload(batch_size=4, sampler_name="DPM adaptive"))
        assert len(jobs) == 1
        assert jobs[0].worker.label == "slow-roomy"
        assert jobs[0].batch_size == 4

    def test_execute_merges_single_job(self):
        w = World(ConfigModel())
        w.add_worker(node("m", 10.0, master=True))
        w.add_worker(node("a", 30.0))
        r = w.execute(payload(batch_size=3, seed=77,
                              sampler_name="DPM adaptive"))
        assert len(r.images) == 3
        assert r.seeds == [77, 78, 79]
        assert set(r.worker_labels) == {"a"}


class TestPinValidation:
    def test_ping_revalidates_unvalidated_pin(self):
        w = World(ConfigModel())
        n = node("a", 10.0)
        n.backend.models = ["good.safetensors", "other.ckpt"]
        w.add_worker(n)
        w.configure_worker("a", model_override="good.safetensors")
        assert n.pin_validated is False  # set without validation
        w.ping_workers()
        assert n.pin_validated is True

    def test_ping_flags_typod_pin(self):
        w = World(ConfigModel())
        n = node("a", 10.0)
        n.backend.models = ["good.safetensors"]
        w.add_worker(n)
        w.configure_worker("a", model_override="typo.safetensors")
        w.ping_workers()
        assert n.pin_validated is False

    def test_clearing_pin_clears_flag(self):
        w = World(ConfigModel())
        n = node("a", 10.0)
        w.add_worker(n)
        w.configure_worker("a", model_override="x")
        w.configure_worker("a", model_override="")
        assert n.model_override is None
        assert n.pin_validated is None

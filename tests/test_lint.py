"""Tier-1 gate and unit tests for sdtpu-lint (the analysis/ package).

Everything here is pure AST work — no JAX device, no imports of the code
under analysis — so the whole file stays in the fast tier.

Three layers:

- the repo gate: the package must analyze clean against the committed
  allowlist (this is the test that fails when someone reintroduces a raw
  ``os.environ`` read, an unguarded shared attribute, or a payload-derived
  static jit argument);
- fixture tests pinning exact rule IDs and line numbers for every rule
  family, plus a clean fixture asserting the exemptions hold;
- allowlist mechanics: suppression, expiry (AL001), unused entries (AL002).
"""

import datetime
import json
import textwrap

import os

from stable_diffusion_webui_distributed_tpu.analysis import (
    RULES,
    analyze_modules,
    run_analysis,
)
from stable_diffusion_webui_distributed_tpu.analysis import (
    allowlist as allowlist_mod,
)
from stable_diffusion_webui_distributed_tpu.analysis.core import load_module

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


def _fixture_findings(name):
    rel = f"tests/lint_fixtures/{name}"
    mod = load_module(os.path.join(REPO, rel), rel)
    return analyze_modules([mod])


def _rule_lines(findings):
    return {(f.rule, f.line) for f in findings}


# -- the repo gate -----------------------------------------------------------

class TestRepoGate:
    def test_package_is_clean(self):
        result = run_analysis(REPO)
        rendered = "\n".join(f.render() for f in result.findings)
        assert result.clean, f"sdtpu-lint findings:\n{rendered}"

    def test_analyzes_the_whole_package(self):
        result = run_analysis(REPO)
        # the package has ~60 modules; a collapse to a handful means the
        # walker broke and the clean gate above is vacuous
        assert result.modules >= 50

    def test_cli_exit_codes(self):
        from stable_diffusion_webui_distributed_tpu.analysis.__main__ import (
            main,
        )

        assert main(["--rules"]) == 0
        assert main([]) == 0  # repo clean vs committed allowlist
        assert main(["--no-allowlist", "tests/lint_fixtures/env_bad.py"]) == 1

    def test_every_rule_has_a_description(self):
        for rule in ("TP001", "TP002", "TP003", "TP004", "RC001", "RC002",
                     "RC003", "EV001", "OB001", "OB002", "OB003", "OB004",
                     "OB005", "LK001", "LK002", "LK003", "LK004", "LK005",
                     "AT001", "TH001", "DN001", "FL001", "AL001", "AL002",
                     "CA001"):
            assert rule in RULES and RULES[rule]


# -- fixture families: exact rule IDs and line numbers -----------------------

class TestFixtures:
    def test_purity_family(self):
        found = _rule_lines(_fixture_findings("purity_bad.py"))
        assert found == {
            ("TP001", 15),  # time.time() in @jax.jit
            ("TP001", 21),  # random.random() in @jax.jit
            ("TP002", 26),  # if x > 0 on a tracer
            ("TP003", 36),  # closed-over dict mutation
        }

    def test_recompile_family(self):
        found = _rule_lines(_fixture_findings("recompile_bad.py"))
        assert found == {
            ("RC001", 16),  # payload.steps as static_argnums arg
            ("RC002", 19),  # closure over payload.width handed to jit
            ("RC001", 35),  # marked factory + closure-inherited taint
        }

    def test_env_family(self):
        found = _rule_lines(_fixture_findings("env_bad.py"))
        assert found == {("EV001", 10), ("EV001", 14)}

    def test_locks_family(self):
        found = _rule_lines(_fixture_findings("locks_bad.py"))
        assert found == {
            ("LK002", 13),  # guarded-by names an unknown lock
            ("LK001", 16),  # unguarded self.total += 1
            ("LK003", 23),  # a->b in ab() vs b->a in ba()
        }

    def test_cadence_family(self):
        # the step-cache knob discipline: a raw env-derived refresh
        # cadence pinned static is RC001; the bucket_cadence-quantized
        # variant in the same fixture must stay clean
        found = _rule_lines(_fixture_findings("cadence_bad.py"))
        assert found == {("RC001", 24)}

    def test_ragged_family(self):
        # the ragged-dispatch length discipline: a request-derived
        # per-row true length pinned static re-mints an executable per
        # height (the ladder explosion ragged dispatch kills); the
        # traced-int32 variant in the same fixture must stay clean
        found = _rule_lines(_fixture_findings("ragged_bad.py"))
        assert found == {("RC001", 20)}

    def test_precision_family(self):
        # the serving-precision discipline (RC003): raw env / override /
        # payload-attribute precision reads bypass the 3-rung ladder in
        # pipeline/precision.py; the bucket_precision-wrapped variant in
        # the same fixture must stay clean
        found = _rule_lines(_fixture_findings("precision_bad.py"))
        assert found == {
            ("RC003", 22),  # raw SDTPU_UNET_INT8 env read
            ("RC003", 23),  # raw override_settings.get("precision")
            ("RC003", 24),  # raw payload.precision attribute read
        }

    def test_lora_family(self):
        # the traced-LoRA ladder discipline: a request-derived adapter
        # rank pinned as a jit static mints one executable per adapter
        # (the recompile storm SDTPU_LORA_TRACED exists to kill); the
        # bucket_rank-quantized variant in the same fixture stays clean
        found = _rule_lines(_fixture_findings("lora_bad.py"))
        assert found == {("RC001", 23)}

    def test_timing_family(self):
        # OB001 is path-scoped: load the fixture under a spoofed serving/
        # rel path so the wall-clock duration reads fire
        rel = "stable_diffusion_webui_distributed_tpu/serving/timing_bad.py"
        mod = load_module(os.path.join(FIXTURES, "timing_bad.py"), rel)
        found = _rule_lines(analyze_modules([mod]))
        assert found == {
            ("OB001", 13),  # t0 = time.time() as a duration start
            ("OB001", 15),  # time.time() - t0
        }
        # perf_counter idiom and the '# sdtpu-lint: wallclock' marker (line
        # 25) stay clean

    def test_timing_rule_is_path_scoped(self):
        # the same file under its real tests/lint_fixtures/ path is out of
        # the serving/pipeline/obs scope: zero findings
        assert not _fixture_findings("timing_bad.py")

    def test_fleet_family(self):
        # FL001 is path-scoped to fleet/ modules: load the fixture under a
        # spoofed fleet/ rel path so the unguarded-container checks fire
        rel = "stable_diffusion_webui_distributed_tpu/fleet/fleet_bad.py"
        mod = load_module(os.path.join(FIXTURES, "fleet_bad.py"), rel)
        found = _rule_lines(analyze_modules([mod]))
        assert found == {
            ("FL001", 16),  # self._entries = [] without guarded-by
            ("FL001", 17),  # self._tags = {}
            ("FL001", 18),  # collections.deque()
        }
        # GoodQueue (annotated) and PolicyTable (no lock) stay clean

    def test_fleet_rule_is_path_scoped(self):
        # the same file under its real tests/lint_fixtures/ path is outside
        # the fleet/ scope: zero FL001 findings (LK001 on the unannotated
        # attrs cannot fire either — they were never declared guarded)
        assert not _fixture_findings("fleet_bad.py")

    def test_metric_family(self):
        # OB002 is package-wide (minus the registry module itself): ad-hoc
        # sdtpu_* metric-name literals must go through register_metric
        rel = "stable_diffusion_webui_distributed_tpu/serving/metric_bad.py"
        mod = load_module(os.path.join(FIXTURES, "metric_bad.py"), rel)
        found = _rule_lines(analyze_modules([mod]))
        assert found == {
            ("OB002", 12),  # hand-rolled metric-name literal
            ("OB002", 17),  # second ad-hoc name inside a function
        }
        # the register_metric() call and the '# sdtpu-lint: metric'
        # marker (non-metric identifier) stay clean

    def test_metric_rule_exempts_registry_module(self):
        # the same literals inside obs/prometheus.py are the registry's
        # own definitions: zero OB002 findings
        rel = "stable_diffusion_webui_distributed_tpu/obs/prometheus.py"
        mod = load_module(os.path.join(FIXTURES, "metric_bad.py"), rel)
        found = _rule_lines(analyze_modules([mod]))
        assert not {f for f in found if f[0] == "OB002"}

    def test_journal_family(self):
        # OB003: every journal.emit literal must come from obs/journal.py
        # EVENTS. The fixture analyzes WITHOUT the registry module, so the
        # registered set is empty and all un-exempt literals fire.
        found = _rule_lines(_fixture_findings("journal_bad.py"))
        assert found == {
            ("OB003", 12),  # module-helper emit, unregistered literal
            ("OB003", 17),  # aliased helper emit inside a function
            ("OB003", 19),  # keyword spelling of the event argument
            ("OB003", 37),  # chaos pin: unregistered without the registry
            ("OB003", 38),  # chaos pin: unregistered without the registry
            ("OB003", 42),  # alert pin: unregistered without the registry
            ("OB003", 43),  # alert pin: unregistered without the registry
            ("OB003", 47),  # notify pin: unregistered without the registry
            ("OB003", 48),  # notify pin: unregistered without the registry
            ("OB003", 49),  # federation pin: same
            ("OB003", 53),  # notify_dropped pin: same
            ("OB003", 54),  # push_buffer_evicted pin: same
            ("OB003", 55),  # push_fallback pin: same
        }
        # dynamic event names, the marker-exempt literal, and plain
        # non-emit strings stay clean

    def test_journal_rule_accepts_registered_events(self):
        # the same emits analyzed WITH the registry module present are
        # checked against its real EVENTS set: a registered name passes
        rel = "stable_diffusion_webui_distributed_tpu/obs/journal.py"
        pkg = os.path.join(
            REPO, "stable_diffusion_webui_distributed_tpu", "obs",
            "journal.py")
        registry = load_module(pkg, rel)
        caller = load_module(
            os.path.join(FIXTURES, "journal_bad.py"),
            "stable_diffusion_webui_distributed_tpu/serving/jb.py")
        found = _rule_lines(analyze_modules([registry, caller]))
        # the bad literals still fire; "completed"-class names would not,
        # the fault_injected/fault_cleared pins (lines 37-38) prove the
        # chaos events are registered in the real vocabulary, and the
        # alert_firing/alert_resolved pins (lines 42-43) the same for
        # the alerting plane
        assert {f for f in found if f[0] == "OB003"} == {
            ("OB003", 12), ("OB003", 17), ("OB003", 19)}

    def test_alert_family(self):
        # OB004: register_rule calls are confined to obs/alerts.py. The
        # fixture analyzes under a spoofed serving/ path — outside the
        # registry module — so both registration shapes fire.
        rel = "stable_diffusion_webui_distributed_tpu/serving/alert_bad.py"
        mod = load_module(os.path.join(FIXTURES, "alert_bad.py"), rel)
        found = _rule_lines(analyze_modules([mod]))
        assert {f for f in found if f[0] == "OB004"} == {
            ("OB004", 12),  # direct registration outside the registry
            ("OB004", 19),  # indirect spelling inside a function
            ("OB004", 30),  # severity literal outside page/warn/info
        }
        # bare AlertRule construction, a valid severity literal, and the
        # '# sdtpu-lint: alert' marker (deliberate plugin site — both the
        # registration and the out-of-set severity shapes) stay clean

    def test_alert_rule_exempts_registry_module(self):
        # the same calls inside obs/alerts.py are the registry's own
        # closed rule set: the registration shapes go quiet. The severity
        # closed-set check is NOT registry-exempt (the registry's own
        # literals route notifications too), so only line 30 fires.
        rel = "stable_diffusion_webui_distributed_tpu/obs/alerts.py"
        mod = load_module(os.path.join(FIXTURES, "alert_bad.py"), rel)
        found = _rule_lines(analyze_modules([mod]))
        assert {f for f in found if f[0] == "OB004"} == {("OB004", 30)}

    def test_net_family(self):
        # OB005: outbound HTTP inside obs/ is confined to
        # federation/notify/stitch. The fixture analyzes under a spoofed
        # obs/ rel path outside the sanctioned trio, so every shape fires.
        rel = "stable_diffusion_webui_distributed_tpu/obs/notify_bad.py"
        mod = load_module(os.path.join(FIXTURES, "notify_bad.py"), rel)
        found = _rule_lines(analyze_modules([mod]))
        assert {f for f in found if f[0] == "OB005"} == {
            ("OB005", 14),  # module-level urllib.request.urlopen
            ("OB005", 19),  # aliased urlopen inside a function
            ("OB005", 21),  # requests verb call
            ("OB005", 23),  # session verb call
        }
        # the '# sdtpu-lint: netcall' marker and the non-HTTP .get on a
        # store stay clean

    def test_net_rule_exempts_sanctioned_modules(self):
        # the same calls inside obs/notify.py are the delivery channel's
        # own outbound path: zero OB005 findings
        rel = "stable_diffusion_webui_distributed_tpu/obs/notify.py"
        mod = load_module(os.path.join(FIXTURES, "notify_bad.py"), rel)
        found = _rule_lines(analyze_modules([mod]))
        assert not {f for f in found if f[0] == "OB005"}

    def test_net_rule_is_path_scoped(self):
        # the same file under its real tests/lint_fixtures/ path is
        # outside the obs/ scope: zero OB005 findings
        found = _rule_lines(_fixture_findings("notify_bad.py"))
        assert not {f for f in found if f[0] == "OB005"}

    def test_cache_family(self):
        # CA001: payload hashing and hand-built cache keys outside
        # cache/keys.py. The fixture analyzes under a serving/ path —
        # outside the sanctioned modules — so both offense shapes fire.
        rel = "stable_diffusion_webui_distributed_tpu/serving/cache_bad.py"
        mod = load_module(os.path.join(FIXTURES, "cache_bad.py"), rel)
        found = _rule_lines(analyze_modules([mod]))
        assert found == {
            ("CA001", 14),  # payload.model_dump() sha256'd directly
            ("CA001", 20),  # .prompt digested outside the key module
            ("CA001", 25),  # hand-built key tuple into a cache .get
            ("CA001", 30),  # same shape on the .put side
        }
        # the keys.result_key call, the marker-exempt digest, file
        # hashing, and tuple keys into non-cache receivers stay clean

    def test_cache_rule_exempts_key_module(self):
        # the same offenses under the sanctioned cache/keys.py path are
        # the key mint itself: zero CA001 findings
        rel = "stable_diffusion_webui_distributed_tpu/cache/keys.py"
        mod = load_module(os.path.join(FIXTURES, "cache_bad.py"), rel)
        found = _rule_lines(analyze_modules([mod]))
        assert not {f for f in found if f[0] == "CA001"}

    def test_donation_family(self):
        found = _rule_lines(_fixture_findings("donate_bad.py"))
        assert found == {
            ("DN001", 13),  # latents read after donate_argnums call
            ("DN001", 27),  # loop-carried donation: dead on iteration 2
            ("DN001", 39),  # donation via a jitted(donate=0) factory
        }
        # rebind_ok (result overwrites the donor in the same statement)
        # and the '# sdtpu-lint: donated' marker (line 45) stay clean

    def test_devicehold_family(self):
        found = _rule_lines(_fixture_findings("devicehold_bad.py"))
        assert found == {
            ("LK004", 19),  # time.sleep under the lock
            ("LK004", 20),  # block_until_ready under the lock
            ("LK004", 27),  # transitive: callee does requests.get
        }
        # cv.wait() on the only held lock and release-before-block stay
        # clean

    def test_tracer_escape_family(self):
        found = _rule_lines(_fixture_findings("tracer_escape_bad.py"))
        assert found == {
            ("TP004", 17),  # tracer stored on self
            ("TP004", 18),  # tracer appended to a self container
        }
        # x.shape (trace-time constant) stays clean

    def test_crossobj_locks_need_no_class_hints(self):
        # LK001 across an object boundary (Registry.peek touches
        # Node.state) and LK003 across two classes, both through inferred
        # attribute types — the hand-maintained CLASS_HINTS table is gone
        found = _rule_lines(_fixture_findings("crossobj_bad.py"))
        assert found == {
            ("LK001", 22),  # self.node.state without Node._lock
            ("LK003", 16),  # Registry.nested vs inverted(), edge owner
        }
        from stable_diffusion_webui_distributed_tpu.analysis import locks
        assert not hasattr(locks, "CLASS_HINTS")

    def test_lockorder_family(self):
        findings = _fixture_findings("lockorder_bad.py")
        found = _rule_lines(findings)
        assert found == {
            ("LK003", 13),  # opposite-order pair (intra-class edge view)
            ("LK005", 13),  # the cycle, walked from both Thread entries
            ("LK005", 36),  # stale annotation: contradicts no edge
        }
        cycle = next(f for f in findings
                     if f.rule == "LK005" and f.line == 13)
        # the finding must carry BOTH acquisition paths, entry-labelled
        assert "path 1:" in cycle.message and "path 2:" in cycle.message
        assert "Pair.a" in cycle.message and "Pair.b" in cycle.message

    def test_lockorder_clean_fixture_is_clean(self):
        findings = _fixture_findings("lockorder_clean.py")
        rendered = "\n".join(f.render() for f in findings)
        assert not findings, \
            f"exercised lockorder annotation must suppress:\n{rendered}"

    def test_atomicity_family(self):
        found = _rule_lines(_fixture_findings("atomicity_bad.py"))
        assert found == {
            ("AT001", 24),  # stale value written back under re-acquire
            ("AT001", 31),  # stale branch gating a locked write
            ("AT001", 59),  # interprocedural: accessor read -> write
        }
        # reserve_ok (fresh re-read validates inside the second critical
        # section) stays clean

    def test_thread_family(self):
        found = _rule_lines(_fixture_findings("thread_bad.py"))
        assert found == {
            ("TH001", 23),  # raw daemon Thread around a looping target
            ("TH001", 34),  # Thread subclass with a looping run()
        }
        # the non-looping one-shot report thread stays clean

    def test_clean_fixture_has_zero_findings(self):
        findings = _fixture_findings("clean.py")
        rendered = "\n".join(f.render() for f in findings)
        assert not findings, f"false positives on clean idioms:\n{rendered}"


# -- interprocedural engine: the cases the old pass provably misses ----------

class TestInterprocedural:
    def _xmod(self, interprocedural):
        mods = [
            load_module(os.path.join(FIXTURES, n),
                        f"tests/lint_fixtures/{n}")
            for n in ("xmod_helper.py", "xmod_consumer.py")
        ]
        return _rule_lines(analyze_modules(
            mods, interprocedural=interprocedural))

    def test_cross_module_taint_found_by_summary_engine(self):
        # raw_steps() lives in another module and returns payload.steps;
        # the consumer feeds its result to a static jit slot
        assert ("RC001", 15) in self._xmod(interprocedural=True)

    def test_cross_module_taint_missed_by_old_intra_pass(self):
        # the same pair under the old per-function pass: a bare call
        # result is never tainted, so the finding is provably absent
        assert not self._xmod(interprocedural=False)

    def test_sanitized_cross_module_path_stays_clean(self):
        # bucketed_steps() routes through bucket_steps(); the summary
        # records the sanitizer and render_bucketed stays clean
        found = self._xmod(interprocedural=True)
        assert found == {("RC001", 15)}


# -- regression injections ---------------------------------------------------
# The acceptance cases: seed a copy of "good" code with one bad edit and the
# analyzer must catch it. These guard against the rules rotting into no-ops.

def _analyze_source(tmp_path, source, name="injected.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    mod = load_module(str(p), name)
    return analyze_modules([mod])


class TestRegressionInjections:
    def test_injected_nondeterminism_in_traced_fn(self, tmp_path):
        findings = _analyze_source(tmp_path, """\
            import time

            import jax


            @jax.jit
            def step(x):
                return x * time.time()
            """)
        assert {f.rule for f in findings} == {"TP001"}

    def test_injected_unguarded_shared_write(self, tmp_path):
        findings = _analyze_source(tmp_path, """\
            import threading


            class Metrics:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.requests = 0  # guarded-by: _lock

                def record(self):
                    self.requests += 1
            """)
        assert {f.rule for f in findings} == {"LK001"}

    def test_injected_nonladder_static_arg(self, tmp_path):
        findings = _analyze_source(tmp_path, """\
            import jax


            def serve(payload):
                fn = jax.jit(lambda x, n: x * n, static_argnums=(1,))
                return fn(payload.latent, payload.steps)
            """)
        assert {f.rule for f in findings} == {"RC001"}

    def test_bucketed_static_arg_is_clean(self, tmp_path):
        findings = _analyze_source(tmp_path, """\
            import jax


            def serve(payload, bucketer):
                fn = jax.jit(lambda x, n: x * n, static_argnums=(1,))
                return fn(payload.latent, bucketer.bucket_batch(payload.steps))
            """)
        assert not findings

    def test_injected_unlocked_cross_object_read(self, tmp_path):
        # pins the server/api.py race this engine caught: handler state
        # arrives through a BoolOp default chain ending in a module
        # singleton, then a guarded attribute is read without the owning
        # object's lock (fixed in the tree via a locked snapshot accessor)
        findings = _analyze_source(tmp_path, """\
            import threading


            class GenerationState:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.progress = 0.0  # guarded-by: _lock


            STATE = GenerationState()


            class Handler:
                def __init__(self, state=None):
                    self.state = state or STATE

                def handle(self):
                    return self.state.progress
            """)
        assert {(f.rule, f.symbol) for f in findings} == {
            ("LK001", "Handler.handle")}

    def test_injected_unlocked_cross_object_write(self, tmp_path):
        # pins the scheduler/world.py finding: writing a guarded attribute
        # on a locally-constructed object instead of its locked setter
        findings = _analyze_source(tmp_path, """\
            import threading


            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.state = "idle"  # guarded-by: _lock


            def from_config():
                node = Worker()
                node.state = "disabled"
                return node
            """)
        assert {(f.rule, f.symbol) for f in findings} == {
            ("LK001", "from_config")}

    def test_injected_blocking_call_under_lock(self, tmp_path):
        findings = _analyze_source(tmp_path, """\
            import threading
            import time


            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()

                def drain(self, fut):
                    with self._lock:
                        time.sleep(1.0)
                        fut.result()
            """)
        assert {f.rule for f in findings} == {"LK004"}
        assert len(findings) == 2

    def test_injected_use_after_donate(self, tmp_path):
        findings = _analyze_source(tmp_path, """\
            import jax


            def step(latents):
                fn = jax.jit(lambda x: x * 2, donate_argnums=(0,))
                out = fn(latents)
                return latents + out
            """)
        assert {f.rule for f in findings} == {"DN001"}

    def test_injected_lock_order_inversion(self, tmp_path):
        # the dynamic half of this pair (the same shape deadlocking
        # under the schedule explorer) lives in tests/test_sched.py
        findings = _analyze_source(tmp_path, """\
            import threading


            class Pair:
                def __init__(self):
                    self.a = threading.Lock()
                    self.b = threading.Lock()

                def forward(self):
                    with self.a:
                        with self.b:
                            pass

                def backward(self):
                    with self.b:
                        with self.a:
                            pass


            def launch():
                p = Pair()
                threading.Thread(target=p.forward, daemon=True).start()
                threading.Thread(target=p.backward, daemon=True).start()
            """)
        assert ("LK005", 4) in {(f.rule, f.line) for f in findings}
        cycle = next(f for f in findings if f.rule == "LK005")
        assert "path 1:" in cycle.message and "path 2:" in cycle.message

    def test_injected_check_then_act_race(self, tmp_path):
        # the dynamic half (lost update under the explorer) lives in
        # tests/test_sched.py
        findings = _analyze_source(tmp_path, """\
            import threading


            class Ledger:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._free = 0  # guarded-by: _lock

                def take(self, n):
                    with self._lock:
                        free = self._free
                    if free >= n:
                        with self._lock:
                            self._free = free - n
            """)
        assert {(f.rule, f.line) for f in findings} == {("AT001", 14)}

    def test_injected_raw_daemon_loop(self, tmp_path):
        findings = _analyze_source(tmp_path, """\
            import threading


            def _poll():
                while True:
                    pass


            def start():
                threading.Thread(target=_poll, daemon=True).start()
            """)
        assert {(f.rule, f.line) for f in findings} == {("TH001", 10)}


# -- cache + --changed mechanics ---------------------------------------------

PKG_GOOD = """\
import os


def read(env):
    return env.get("X")
"""

PKG_BAD = """\
import os


def read():
    return os.environ.get("X")  # EV001
"""


def _mini_tree(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text(PKG_GOOD)
    (pkg / "b.py").write_text(PKG_BAD)
    return pkg


class TestCache:
    def _run(self, root, **kw):
        return run_analysis(str(root), paths=["pkg"], use_allowlist=False,
                            use_cache=True, **kw)

    def test_second_run_hits_and_preserves_findings(self, tmp_path):
        _mini_tree(tmp_path)
        first = self._run(tmp_path)
        assert not first.cache_hit
        assert {f.rule for f in first.findings} == {"EV001"}
        second = self._run(tmp_path)
        assert second.cache_hit
        assert _rule_lines(second.findings) == _rule_lines(first.findings)

    def test_edit_invalidates_by_content_hash(self, tmp_path):
        pkg = _mini_tree(tmp_path)
        self._run(tmp_path)
        # same mtime games don't matter: the key is the content hash
        (pkg / "b.py").write_text(PKG_BAD.replace('"X"', '"Y"'))
        third = self._run(tmp_path)
        assert not third.cache_hit
        assert {f.rule for f in third.findings} == {"EV001"}

    def test_changed_scope_filters_to_dirty_dependents(self, tmp_path):
        import subprocess

        pkg = _mini_tree(tmp_path)
        env = dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                   GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")
        for cmd in (["git", "init", "-q"], ["git", "add", "."],
                    ["git", "commit", "-qm", "seed"]):
            subprocess.run(cmd, cwd=tmp_path, env=env, check=True)
        clean = run_analysis(str(tmp_path), paths=["pkg"],
                             use_allowlist=False, changed_only=True)
        # nothing changed since HEAD: the report scope is empty even
        # though b.py still has a finding under the full gate
        assert not clean.findings
        (pkg / "b.py").write_text(PKG_BAD + "\n# touched\n")
        dirty = run_analysis(str(tmp_path), paths=["pkg"],
                             use_allowlist=False, changed_only=True)
        assert {f.rule for f in dirty.findings} == {"EV001"}


# -- allowlist mechanics -----------------------------------------------------

def _write_allowlist(tmp_path, entries):
    p = tmp_path / "allowlist.json"
    p.write_text(json.dumps(entries))
    return str(p)


ENV_BAD = "tests/lint_fixtures/env_bad.py"


class TestAllowlist:
    def test_entry_suppresses_matching_finding(self, tmp_path):
        path = _write_allowlist(tmp_path, [{
            "rule": "EV001", "path": ENV_BAD, "symbol": "read_knob",
            "reason": "fixture exercise"}])
        result = run_analysis(REPO, paths=[ENV_BAD], allowlist_path=path)
        assert len(result.suppressed) == 1
        assert {(f.rule, f.symbol) for f in result.findings} == {
            ("EV001", "read_flag")}

    def test_expired_entry_resurfaces_finding_and_reports_al001(
            self, tmp_path):
        path = _write_allowlist(tmp_path, [{
            "rule": "EV001", "path": ENV_BAD, "symbol": "read_knob",
            "reason": "dated debt", "expires": "2026-01-01"}])
        result = run_analysis(REPO, paths=[ENV_BAD], allowlist_path=path,
                              today=datetime.date(2026, 6, 1))
        rules = sorted(f.rule for f in result.findings)
        assert rules == ["AL001", "EV001", "EV001"]
        assert not result.suppressed

    def test_entry_still_live_before_expiry(self, tmp_path):
        path = _write_allowlist(tmp_path, [{
            "rule": "EV001", "path": ENV_BAD, "symbol": "read_knob",
            "reason": "dated debt", "expires": "2026-01-01"}])
        result = run_analysis(REPO, paths=[ENV_BAD], allowlist_path=path,
                              today=datetime.date(2025, 6, 1))
        assert sorted(f.rule for f in result.findings) == ["EV001"]
        assert len(result.suppressed) == 1

    def test_unused_entry_reports_al002(self, tmp_path):
        path = _write_allowlist(tmp_path, [{
            "rule": "TP001", "path": "nowhere.py", "symbol": "ghost",
            "reason": "stale"}])
        result = run_analysis(REPO, paths=[ENV_BAD], allowlist_path=path)
        assert "AL002" in {f.rule for f in result.findings}

    def test_unparseable_expiry_fails_safe(self):
        e = allowlist_mod.Entry(rule="EV001", path="p", symbol="s",
                                reason="r", expires="not-a-date")
        assert e.expired(datetime.date(2020, 1, 1))

    def test_committed_allowlist_loads_and_is_a_list(self):
        entries, path = allowlist_mod.load()
        assert path.endswith("allowlist.json")
        assert isinstance(entries, list)

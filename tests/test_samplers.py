"""Sampler tests: schedule shapes/monotonicity, convergence on an analytic
denoiser, seed-exact sharding of ancestral noise, chunked == unchunked."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.runtime import rng
from stable_diffusion_webui_distributed_tpu.samplers import (
    kdiffusion as kd,
    schedules as sched,
)

SCHEDULE = sched.sd_schedule()


def keys_for(seed, n):
    return jax.vmap(lambda i: rng.key_for_image(seed, i))(jnp.arange(n))


class TestSchedules:
    def test_trained_sigma_range(self):
        # SD's scaled-linear schedule: sigma_min ~0.03, sigma_max ~14.6.
        assert 0.02 < SCHEDULE.sigma_min < 0.04
        assert 14.0 < SCHEDULE.sigma_max < 15.5

    @pytest.mark.parametrize("name", ["default", "karras", "ddim", "exponential"])
    def test_ladder_shape_and_monotone(self, name):
        s = sched.SCHEDULES[name](SCHEDULE, 20)
        assert s.shape == (21,)
        assert s[-1] == 0.0
        assert np.all(np.diff(s) < 0), f"{name} not strictly decreasing"

    def test_sigma_t_roundtrip(self):
        t = SCHEDULE.sigma_to_t(jnp.float32(1.0))
        back = SCHEDULE.t_to_sigma(t)
        np.testing.assert_allclose(float(back), 1.0, rtol=1e-3)


class TestSamplerMath:
    """Analytic check: with denoise_fn(x, sigma) = x0 (a perfect denoiser for
    a point distribution at x0), every deterministic sampler must land on x0
    from any start, and ancestral ones must land near it."""

    X0 = 3.7

    def _run(self, name, steps=12, x0=None):
        spec = kd.resolve_sampler(name)
        x0 = self.X0 if x0 is None else x0

        def denoise(x, sigma, step):
            return jnp.full_like(x, x0)

        sigmas = kd.build_sigmas(spec, SCHEDULE, steps)
        keys = keys_for(7, 2)
        step = kd.make_sampler_step(spec, denoise, sigmas, keys)
        x = jnp.full((2, 4, 4, 1), 10.0) * sigmas[0] / 10.0  # scaled start
        carry = kd.run_steps(step, kd.init_carry(x), 0, steps)
        return np.asarray(carry.x)

    @pytest.mark.parametrize(
        "name", ["Euler", "DDIM", "Heun", "DPM++ 2M", "DPM++ 2M Karras",
                 "LMS", "DPM2", "PLMS", "DPM fast", "DPM adaptive"])
    def test_deterministic_converges_exactly(self, name):
        out = self._run(name)
        np.testing.assert_allclose(out, self.X0, rtol=1e-4, atol=1e-4)

    # Euler's loose bound is the 1st-order contrast anchor (the ladder tail
    # is stiff for x ∝ sigma^0.3). PLMS's constant-coefficient
    # Adams-Bashforth roughly halves Euler's error (as ldm's does on stiff
    # tails); the DPM solvers must track the exact solution 100x+ tighter.
    @pytest.mark.parametrize("name,rel_tol", [
        ("Euler", 0.80), ("PLMS", 0.40), ("DPM fast", 0.15),
        ("DPM adaptive", 0.005)])
    def test_order_of_accuracy_on_analytic_ode(self, name, rel_tol):
        """Integrate dx/dsigma = x(1-k)/sigma (denoiser x0 = k*x), whose
        exact solution is x ∝ sigma^(1-k). Higher-order samplers must track
        it far better than Euler at the same step count; stop one step
        before the terminal sigma=0 (where every sampler is exact anyway).
        """
        k = 0.7
        spec = kd.resolve_sampler(name)

        def denoise(x, sigma, step):
            return x * k

        steps = 12
        sigmas = kd.build_sigmas(spec, SCHEDULE, steps)
        keys = keys_for(3, 1)
        step = kd.make_sampler_step(spec, denoise, sigmas, keys)
        x = jnp.full((1, 2, 2, 1), float(sigmas[0]))
        carry = kd.run_steps(step, kd.init_carry(x), 0, steps - 1)
        got = float(np.asarray(carry.x).mean())
        exact = float(sigmas[0]) * (float(sigmas[steps - 1])
                                    / float(sigmas[0])) ** (1 - k)
        assert abs(got - exact) / exact < rel_tol, (got, exact)

    @pytest.mark.parametrize(
        "name", ["Euler a", "DPM2 a", "DPM++ 2S a", "DPM++ SDE",
                 "DPM++ 2S a Karras", "DPM++ SDE Karras"])
    def test_ancestral_converges(self, name):
        # Ancestral noise is annealed by sigma_up -> 0 at the end; the final
        # x must be exactly x0 because the terminal step has sigma_next=0.
        out = self._run(name)
        np.testing.assert_allclose(out, self.X0, rtol=1e-3, atol=1e-3)

    def test_unknown_name_falls_back_to_euler_a(self):
        spec = kd.resolve_sampler("No Such Sampler")
        assert spec.algorithm == "euler_a"  # reference worker.py:457-467


class TestDpmAdaptive:
    """The host-side PID loop (kd.sample_dpm_adaptive): k-diffusion's
    adaptive controller over the compiled embedded order-2/3 pair."""

    def _attempt(self, denoise):
        return jax.jit(kd.make_adaptive_attempt(denoise))

    def test_exact_on_point_denoiser(self):
        # denoised == const: the exponential integrator is exact, every
        # attempt is accepted, and x lands on the analytic solution
        # x(sigma) = x0 + (x_start - x0) * sigma/sigma_start.
        x0 = 2.5

        def denoise(x, sigma, step):
            return jnp.full_like(x, x0)

        smax, smin = float(SCHEDULE.sigma_max), float(SCHEDULE.sigma_min)
        x = jnp.full((2, 4, 4, 1), x0 + smax)  # offset = sigma_max
        out, info = kd.sample_dpm_adaptive(self._attempt(denoise), x,
                                           smax, smin)
        exact = x0 + smin  # offset decays proportionally to sigma
        np.testing.assert_allclose(np.asarray(out), exact, rtol=1e-3,
                                   atol=1e-3)
        assert info["n_reject"] == 0
        assert info["nfe"] == 3 * info["steps"]
        # the PID grows h on exact solves: far fewer steps than a dense
        # fixed ladder would need to cross ~6 decades of sigma
        assert info["n_accept"] < 200

    def test_tracks_analytic_ode_tightly(self):
        # same ODE family as test_order_of_accuracy_on_analytic_ode
        k = 0.7

        def denoise(x, sigma, step):
            return x * k

        smax, smin = float(SCHEDULE.sigma_max), 0.1
        x = jnp.full((1, 2, 2, 1), smax)
        out, info = kd.sample_dpm_adaptive(self._attempt(denoise), x,
                                           smax, smin)
        exact = smax * (smin / smax) ** (1 - k)
        got = float(np.asarray(out).mean())
        assert abs(got - exact) / exact < 0.05, (got, exact, info)
        # tightening rtol/atol must tighten the result (the controller
        # actually controls): an order tighter tolerance, ~2x+ less error
        out2, info2 = kd.sample_dpm_adaptive(
            self._attempt(denoise), x, smax, smin, rtol=0.005, atol=8e-4)
        got2 = float(np.asarray(out2).mean())
        assert abs(got2 - exact) < abs(got - exact) / 2, (got, got2, info2)
        assert info2["n_accept"] > info["n_accept"]

    def test_interrupt_stops_between_attempts(self):
        def denoise(x, sigma, step):
            return jnp.zeros_like(x)

        calls = []
        x = jnp.full((1, 2, 2, 1), 10.0)
        out, info = kd.sample_dpm_adaptive(
            self._attempt(denoise), x, 10.0, 0.1,
            should_stop=lambda: len(calls) >= 2 or calls.append(None))
        assert info["steps"] == 2  # stopped after two attempts

    def test_on_accept_transforms_every_accepted_step(self):
        def denoise(x, sigma, step):
            return jnp.zeros_like(x)

        seen = []

        def on_accept(x, sigma, n):
            seen.append((n, sigma))
            return x

        _, info = kd.sample_dpm_adaptive(
            self._attempt(denoise), jnp.full((1, 2, 2, 1), 10.0),
            10.0, 0.5, on_accept=on_accept)
        assert [n for n, _ in seen] == list(range(1, info["n_accept"] + 1))
        assert all(s2 < s1 for (_, s1), (_, s2) in zip(seen, seen[1:]))

    def test_spec_is_marked_adaptive(self):
        assert kd.resolve_sampler("DPM adaptive").adaptive
        assert not kd.resolve_sampler("Euler a").adaptive


class TestShardingContract:
    """Ancestral noise must depend only on the image's key — never on batch
    position — so sub-batches reproduce the full batch exactly."""

    def test_subbatch_equals_fullbatch_ancestral(self):
        spec = kd.resolve_sampler("Euler a")
        shape = (4, 4, 1)

        def denoise(x, sigma, step):
            # any x-dependent denoiser; keeps the test honest
            return x * 0.9 / (1.0 + sigma)

        sigmas = kd.build_sigmas(spec, SCHEDULE, 8)
        full_keys = keys_for(123, 6)
        x_full = rng.batch_noise(123, 0, 0.0, 0, 6, shape) * sigmas[0]
        step = kd.make_sampler_step(spec, denoise, sigmas, full_keys)
        out_full = np.asarray(
            kd.run_steps(step, kd.init_carry(x_full), 0, 8).x
        )

        # images [2, 5) as an independent sub-batch (another "worker")
        sub_keys = jax.vmap(lambda i: rng.key_for_image(123, i))(
            jnp.arange(2, 5))
        x_sub = rng.batch_noise(123, 0, 0.0, 2, 3, shape) * sigmas[0]
        step_sub = kd.make_sampler_step(spec, denoise, sigmas, sub_keys)
        out_sub = np.asarray(
            kd.run_steps(step_sub, kd.init_carry(x_sub), 0, 8).x
        )
        np.testing.assert_array_equal(out_full[2:5], out_sub)


class TestChunking:
    def test_chunked_equals_unchunked(self):
        """Interrupt chunking must not change results (worker.py:440-448
        semantics: polling is invisible to the computation)."""
        spec = kd.resolve_sampler("Euler a")

        def denoise(x, sigma, step):
            return x / (1.0 + sigma)

        sigmas = kd.build_sigmas(spec, SCHEDULE, 10)
        keys = keys_for(9, 2)
        x = rng.batch_noise(9, 0, 0.0, 0, 2, (4, 4, 1)) * sigmas[0]
        step = kd.make_sampler_step(spec, denoise, sigmas, keys)

        whole = kd.run_steps(step, kd.init_carry(x), 0, 10)
        c = kd.init_carry(x)
        for lo, hi in [(0, 3), (3, 7), (7, 10)]:
            c = kd.run_steps(step, c, lo, hi)
        np.testing.assert_array_equal(np.asarray(whole.x), np.asarray(c.x))

"""Caching tier (cache/): keys, bounded store, embed dedupe, result
dedupe with single-flight, denoise prefix sharing.

The contract under test is byte-identity everywhere:

- gate off (default): the dispatch path produces the same bytes as
  before the tier existed (and the gated-on FIRST run of a payload — all
  misses — matches the gate-off run, so arming the cache never changes
  pixels);
- a result-dedupe hit returns the cached images byte-for-byte with ZERO
  new device dispatches, and never feeds the queue-wait histogram or the
  ETA calibration;
- a prefix-shared request resumes mid-trajectory and still produces the
  bytes of an uncached full denoise;
- N concurrent identical requests collapse to one generation
  (single-flight), all N returning identical bytes.
"""

import sys
import threading

import numpy as np
import pytest

from stable_diffusion_webui_distributed_tpu import cache
from stable_diffusion_webui_distributed_tpu.cache import keys as cache_keys
from stable_diffusion_webui_distributed_tpu.cache import (
    prefix as cache_prefix,
)
from stable_diffusion_webui_distributed_tpu.cache.store import (
    BoundedStore, SingleFlight,
)
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.obs import journal as obs_journal
from stable_diffusion_webui_distributed_tpu.obs import (
    prometheus as obs_prom,
)
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.pipeline.stepcache import (
    prefix_boundary,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.serving.bucketer import (
    ShapeBucketer,
)
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (
    ServingDispatcher,
)
from stable_diffusion_webui_distributed_tpu.serving.metrics import METRICS
from test_pipeline import init_params

sys.path.insert(0, "tools")

import replay  # noqa: E402  (tools/ on path)


def payload(**kw):
    defaults = dict(prompt="a cow", steps=8, width=32, height=32,
                    seed=7, sampler_name="Euler a")
    defaults.update(kw)
    return GenerationPayload(**defaults)


@pytest.fixture(scope="module")
def engine():
    return Engine(TINY, init_params(TINY), chunk_size=4,
                  state=GenerationState())


def dispatcher(engine):
    return ServingDispatcher(
        engine, bucketer=ShapeBucketer(shapes=[(32, 32)], batches=[1]),
        window=0.0)


@pytest.fixture()
def cache_on(monkeypatch):
    monkeypatch.setenv("SDTPU_CACHE", "1")
    cache.clear_all()
    obs_prom.CACHE_COUNTER.clear()
    yield
    cache.clear_all()
    obs_prom.CACHE_COUNTER.clear()


# -- keys --------------------------------------------------------------------

class TestKeys:
    FP = ("m", "fam", 0, 0, 0)

    def test_result_key_canonical_under_field_order_and_defaults(self):
        a = payload(seed=3)
        # same request with a default spelled out explicitly and fields
        # built in a different order: one content address
        b = GenerationPayload(seed=3, sampler_name="Euler a", height=32,
                              width=32, steps=8, prompt="a cow",
                              cfg_scale=7.0, n_iter=1)
        assert cache_keys.result_key(a, self.FP, "txt2img") == \
            cache_keys.result_key(b, self.FP, "txt2img")

    def test_result_key_volatile_and_material_fields(self):
        a = payload(seed=3, request_id="r-1")
        b = payload(seed=3, request_id="r-2")
        c = payload(seed=4, request_id="r-1")
        k = cache_keys.result_key
        assert k(a, self.FP, "txt2img") == k(b, self.FP, "txt2img")
        assert k(a, self.FP, "txt2img") != k(c, self.FP, "txt2img")
        assert k(a, self.FP, "txt2img") != k(a, self.FP, "img2img")
        assert k(a, self.FP, "txt2img") != \
            k(a, ("m", "fam", 1, 0, 0), "txt2img")

    def test_embed_key_binds_text_skip_and_model(self):
        k = cache_keys.embed_key
        base = k("a cow", 0, 1, self.FP)
        assert base == k("a cow", 0, 1, self.FP)
        assert base != k("a dog", 0, 1, self.FP)
        assert base != k("a cow", 1, 1, self.FP)
        assert base != k("a cow", 0, 2, self.FP)
        assert base != k("a cow", 0, 1, ("m", "fam", 1, 0, 0))
        assert base != k("a cow", 0, 1, self.FP, tower_fp=((77,), ()))

    def test_prefix_key_ignores_post_prefix_divergence(self):
        kw = dict(model_fp=self.FP, batch=1, width=32, height=32,
                  steps=8, cadence=1, sc_active=False, precision="bf16")
        base = cache_keys.prefix_key(payload(seed=3), **kw)
        # fields that only shape the trajectory after the shared prefix
        # (or volatile identity) do not move the key
        assert base == cache_keys.prefix_key(
            payload(seed=3, request_id="x", denoising_strength=0.42,
                    hr_scale=2.0), **kw)
        assert base == cache_keys.prefix_key(
            payload(seed=3, override_settings={"cfg_cutoff": 1.5}), **kw)
        # everything that influences the prefix does
        assert base != cache_keys.prefix_key(payload(seed=4), **kw)
        assert base != cache_keys.prefix_key(
            payload(seed=3, override_settings={"deepcache": 2}), **kw)
        assert base != cache_keys.prefix_key(
            payload(seed=3), **{**kw, "sc_active": True})
        assert base != cache_keys.prefix_key(
            payload(seed=3), **{**kw, "precision": "int8"})
        assert base != cache_keys.prefix_key(
            payload(seed=3), **{**kw, "cadence": 2})

    def test_prefix_boundary_rules(self):
        assert prefix_boundary(4, 1, 8, 4)
        assert not prefix_boundary(3, 1, 8, 4)      # below min_steps
        assert not prefix_boundary(5, 2, 8, 4)      # off-cadence
        assert prefix_boundary(6, 2, 8, 4)
        assert not prefix_boundary(6, 1, 5, 4)      # past the CFG cutoff


# -- bounded store + single flight -------------------------------------------

class TestBoundedStore:
    def test_lru_eviction_under_byte_cap(self):
        s = BoundedStore("t", max_bytes=100)
        assert s.put("a", 1, 40) and s.put("b", 2, 40)
        assert s.get("a") == 1          # refresh a: b is now LRU
        assert s.put("c", 3, 40)        # over cap -> evict b
        assert s.get("b") is None and s.get("a") == 1 and s.get("c") == 3
        st = s.stats()
        assert st["entries"] == 2 and st["bytes"] == 80
        assert st["evictions"] == 1 and st["puts"] == 3
        assert st["hits"] == 3 and st["misses"] == 1
        assert st["hit_rate"] == pytest.approx(0.75)

    def test_oversized_entry_refused(self):
        s = BoundedStore("t", max_bytes=10)
        assert not s.put("big", 1, 11)
        assert len(s) == 0 and s.stats()["puts"] == 0

    def test_peek_does_not_count(self):
        s = BoundedStore("t", max_bytes=10)
        s.put("a", 1, 1)
        assert s.peek("a") == 1 and s.peek("zz") is None
        assert s.stats()["hits"] == 0 and s.stats()["misses"] == 0

    def test_single_flight_election_and_publish(self):
        sf = SingleFlight()
        role1, f1 = sf.acquire("k")
        assert role1 == "leader"
        got = []

        def follow():
            role, f = sf.acquire("k")
            assert role == "wait"
            f.event.wait(5.0)
            got.append(f.value)

        ts = [threading.Thread(target=follow) for _ in range(3)]
        for t in ts:
            t.start()
        sf.publish("k", f1, "result")
        for t in ts:
            t.join()
        assert got == ["result"] * 3
        assert sf.stats() == {"led": 1, "joined": 3, "inflight": 0}

    def test_abandon_wakes_followers_for_reelection(self):
        sf = SingleFlight()
        _role, f1 = sf.acquire("k")
        outcome = []

        def follow():
            role, f = sf.acquire("k")
            f.event.wait(5.0)
            outcome.append((role, f.value))

        t = threading.Thread(target=follow)
        t.start()
        while sf.stats()["joined"] < 1:
            pass
        sf.abandon("k", f1)
        t.join()
        assert outcome == [("wait", None)]  # woken empty: caller re-elects


# -- gate-off / first-run byte identity --------------------------------------

class TestByteIdentity:
    def test_gate_off_and_armed_first_run_match(self, engine, monkeypatch):
        monkeypatch.delenv("SDTPU_CACHE", raising=False)
        p = payload(seed=11, prompt="byte identity cow")
        off = dispatcher(engine).submit(p.model_copy())

        monkeypatch.setenv("SDTPU_CACHE", "1")
        cache.clear_all()
        on = dispatcher(engine).submit(p.model_copy())
        cache.clear_all()
        assert off.images == on.images
        assert off.infotexts == on.infotexts
        assert off.seeds == on.seeds


# -- embed dedupe ------------------------------------------------------------

class TestEmbedCache:
    def test_second_request_hits_both_halves(self, engine, cache_on):
        disp = dispatcher(engine)
        # different seeds -> different result keys: the embed layer is
        # what dedupes, not the result layer
        disp.submit(payload(seed=21, prompt="embed cow"))
        s1 = cache.embed_layer.summary()
        assert s1["positive"]["misses"] >= 1
        assert s1["negative"]["misses"] >= 1
        assert s1["positive"]["hits"] == 0
        disp.submit(payload(seed=22, prompt="embed cow"))
        s2 = cache.embed_layer.summary()
        assert s2["positive"]["hits"] == s1["positive"]["misses"]
        assert s2["negative"]["hits"] == s1["negative"]["misses"]
        assert s2["positive"]["misses"] == s1["positive"]["misses"]
        assert s2["bytes"] > 0

    def test_lora_epoch_retires_conditioning(self, engine, cache_on):
        fp1 = cache_keys.model_fingerprint(engine)
        engine._model_epoch += 1  # what set_loras/set_vae do
        try:
            assert cache_keys.model_fingerprint(engine) != fp1
        finally:
            engine._model_epoch -= 1


# -- result dedupe -----------------------------------------------------------

class TestResultDedupe:
    def test_hit_is_byte_exact_with_zero_dispatches(self, engine, cache_on):
        disp = dispatcher(engine)
        p = payload(seed=31, prompt="dedupe cow")
        METRICS.clear()
        first = disp.submit(p.model_copy())
        assert METRICS.summary()["dispatches"] == 1
        second = disp.submit(p.model_copy())
        assert METRICS.summary()["dispatches"] == 1  # served, not run
        assert METRICS.summary()["requests"] == 1    # admission untouched
        assert second.images == first.images
        assert second.infotexts == first.infotexts
        assert second.images is not first.images     # defensive copy
        st = cache.result_store().stats()
        assert st["hits"] == 1 and st["puts"] == 1

    def test_single_flight_collapses_concurrent_repeats(self, engine,
                                                        cache_on):
        disp = dispatcher(engine)
        p = payload(seed=32, prompt="single flight cow")
        METRICS.clear()
        results = [None] * 6
        errors = []

        def run(i):
            try:
                results[i] = disp.submit(p.model_copy())
            except Exception as e:  # noqa: BLE001 — surfaced by assert
                errors.append(e)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert METRICS.summary()["dispatches"] == 1  # one generation
        for r in results[1:]:
            assert r.images == results[0].images
        sf = cache.FLIGHTS.stats()
        assert sf["led"] == 1 and sf["inflight"] == 0

    def test_distinct_seeds_never_coalesce_in_cache(self, engine,
                                                    cache_on):
        disp = dispatcher(engine)
        a = disp.submit(payload(seed=33, prompt="cache cow"))
        b = disp.submit(payload(seed=34, prompt="cache cow"))
        assert a.images != b.images or a.seeds != b.seeds
        assert cache.result_store().stats()["puts"] == 2


# -- denoise prefix sharing --------------------------------------------------

class TestPrefixSharing:
    def test_resume_is_byte_identical_to_full_denoise(self, engine,
                                                      monkeypatch):
        # A and B share the full trajectory (denoising_strength is inert
        # for plain txt2img) but have different result keys, so B is
        # served by the PREFIX layer, resuming mid-denoise from A's
        # captured carry — and must still match an uncached full run.
        monkeypatch.delenv("SDTPU_CACHE", raising=False)
        p_b = payload(seed=41, prompt="prefix cow", steps=8,
                      sampler_name="DPM++ 2M", denoising_strength=0.7)
        full = dispatcher(engine).submit(p_b.model_copy())

        monkeypatch.setenv("SDTPU_CACHE", "1")
        cache.clear_all()
        disp = dispatcher(engine)
        p_a = payload(seed=41, prompt="prefix cow", steps=8,
                      sampler_name="DPM++ 2M", denoising_strength=0.4)
        disp.submit(p_a.model_copy())
        assert cache_prefix.summary()["captured"] == 1

        resumed = disp.submit(p_b.model_copy())
        s = cache_prefix.summary()
        assert s["resumed"] == 1
        assert resumed.images == full.images
        assert resumed.infotexts == full.infotexts
        cache.clear_all()

    def test_min_steps_floor_blocks_shallow_capture(self, engine,
                                                    cache_on, monkeypatch):
        monkeypatch.setenv("SDTPU_CACHE_PREFIX_MIN_STEPS", "16")
        disp = dispatcher(engine)
        disp.submit(payload(seed=42, prompt="shallow cow", steps=8))
        assert cache_prefix.summary()["captured"] == 0

    def test_multi_image_requests_not_prefix_keyed_per_group(self, engine,
                                                             cache_on):
        # batch_size*n_iter == latent batch here, so plan() accepts; the
        # guard under test is exercised directly
        assert cache_prefix.plan(
            engine, payload(seed=43, batch_size=2), batch=1, width=32,
            height=32, steps=8, end=8, cadence=1, sc_active=False,
            precision="bf16", cfg_stop=8) is None


# -- accounting isolation (ETA / queue-wait) ---------------------------------

class TestAccountingIsolation:
    def test_dedupe_burst_leaves_eta_and_queue_wait_untouched(
            self, engine, cache_on):
        disp = dispatcher(engine)
        p = payload(seed=51, prompt="eta cow")
        disp.submit(p.model_copy())  # generates + publishes

        def eta_line():
            return [ln for ln in obs_prom.render().splitlines()
                    if ln.startswith("sdtpu_eta_mpe_percent")]

        before_eta = eta_line()
        before_samples = obs_prom.ETA_GAUGE.summary()["samples"]
        before_wait = obs_prom.HISTOGRAMS["queue_wait"].snapshot()
        before_requests = METRICS.summary()["requests"]
        before_avg_wait = METRICS.avg_queue_wait()

        for _ in range(5):  # burst of byte-exact repeats: all hits
            disp.submit(p.model_copy())

        assert eta_line() == before_eta
        assert obs_prom.ETA_GAUGE.summary()["samples"] == before_samples
        assert obs_prom.HISTOGRAMS["queue_wait"].snapshot() == before_wait
        assert METRICS.summary()["requests"] == before_requests
        assert METRICS.avg_queue_wait() == before_avg_wait


# -- journal + replay --------------------------------------------------------

@pytest.fixture()
def journal_on(monkeypatch):
    monkeypatch.setenv("SDTPU_JOURNAL", "1")
    obs_journal.JOURNAL.clear()
    yield obs_journal.JOURNAL
    obs_journal.JOURNAL.clear()


class TestJournal:
    def test_cache_events_and_replay_reconstruction(self, engine, cache_on,
                                                    journal_on):
        disp = dispatcher(engine)
        disp.submit(payload(seed=61, prompt="journal cow",
                            request_id="rid-lead"))
        disp.submit(payload(seed=61, prompt="journal cow",
                            request_id="rid-hit"))
        # same prompt, new seed: embed hits, no result hit
        disp.submit(payload(seed=62, prompt="journal cow",
                            request_id="rid-embed"))

        snap = journal_on.snapshot()
        hit_events = [e["event"]
                      for e in replay.events_for(snap, "rid-hit")]
        assert "result_dedupe_hit" in hit_events
        assert hit_events[-1] == "completed"
        assert "dispatched" not in hit_events
        embed_events = [e["event"]
                        for e in replay.events_for(snap, "rid-embed")]
        assert "embed_cache_hit" in embed_events

        # a journaled dedupe-hit request still reconstructs for replay
        plan = replay.reconstruct(replay.events_for(snap, "rid-hit"))
        assert plan.request_id == "rid-hit"
        assert plan.outcome["status"] == "completed"
        assert plan.payload["seed"] == 61

    def test_prefix_resume_is_journaled(self, engine, cache_on,
                                        journal_on):
        disp = dispatcher(engine)
        disp.submit(payload(seed=63, prompt="journal prefix cow",
                            denoising_strength=0.4, request_id="rid-a"))
        disp.submit(payload(seed=63, prompt="journal prefix cow",
                            denoising_strength=0.7, request_id="rid-b"))
        snap = journal_on.snapshot()
        evs = {e["event"]: e for e in replay.events_for(snap, "rid-b")}
        assert "prefix_resumed" in evs
        assert evs["prefix_resumed"]["attrs"]["step"] == 4


# -- /internal/cache ---------------------------------------------------------

class TestEndpoint:
    def _server(self):
        from stable_diffusion_webui_distributed_tpu.server.api import (
            ApiServer,
        )

        class BareSource:
            pass

        return ApiServer(BareSource(), state=GenerationState())

    def test_route_and_gate_off_body(self, monkeypatch):
        monkeypatch.delenv("SDTPU_CACHE", raising=False)
        srv = self._server()
        assert ("GET", "/internal/cache") in srv.routes()
        assert srv.handle_cache() == {"enabled": False}

    def test_exact_schema_snapshot(self, cache_on):
        body = self._server().handle_cache()
        assert sorted(body) == ["embed", "enabled", "prefix", "result"]
        assert body["enabled"] is True
        store_keys = ["bytes", "entries", "evictions", "hit_rate", "hits",
                      "max_bytes", "misses", "puts"]
        assert sorted(body["embed"]) == sorted(
            store_keys + ["positive", "negative"])
        assert sorted(body["embed"]["positive"]) == [
            "hit_rate", "hits", "misses"]
        assert sorted(body["result"]) == sorted(
            store_keys + ["single_flight"])
        assert sorted(body["result"]["single_flight"]) == [
            "inflight", "joined", "led"]
        assert sorted(body["prefix"]) == sorted(
            store_keys + ["captured", "resumed"])

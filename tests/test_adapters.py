"""LoRA + ControlNet tests: key mapping, merge math, prompt syntax,
zero-residual identity, end-to-end engine behavior (BASELINE configs #3/#4).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.models import lora as lora_mod
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.models.controlnet import (
    ControlNet,
    convert_controlnet,
    preprocess_canny,
    run_preprocessor,
)
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
    array_to_b64png,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)

from test_models import _conv, _lin, _norm, _ldm_res, _ldm_xformer
from test_pipeline import init_params

RNG = np.random.default_rng(7)


def make_lora_sd(dim=32, rank=4, scale=1.0):
    """Synthetic kohya LoRA touching TINY's first UNet attention q and the
    text encoder's layer-0 q projection."""
    sd = {}
    for module, d in [
        ("lora_unet_input_blocks_1_1_transformer_blocks_0_attn1_to_q", dim),
        ("lora_te_text_model_encoder_layers_0_self_attn_q_proj", 32),
    ]:
        sd[f"{module}.lora_down.weight"] = (
            RNG.standard_normal((rank, d), np.float32) * scale)
        sd[f"{module}.lora_up.weight"] = (
            RNG.standard_normal((d, rank), np.float32) * scale)
        sd[f"{module}.alpha"] = np.float32(rank)
    return sd


class TestLoraMapping:
    def test_merge_touches_only_target_slice(self):
        params = init_params(TINY)
        sd = make_lora_sd()
        merged, applied, skipped = lora_mod.merge_lora(params, sd, 1.0, TINY)
        assert applied == 2 and skipped == 0
        base_qkv = np.asarray(
            params["unet"]["down_0_attn_0"]["block_0"]["attn1"]["qkv"]["kernel"])
        new_qkv = np.asarray(
            merged["unet"]["down_0_attn_0"]["block_0"]["attn1"]["qkv"]["kernel"])
        C = base_qkv.shape[1] // 3
        assert not np.allclose(base_qkv[:, :C], new_qkv[:, :C])   # q changed
        np.testing.assert_array_equal(base_qkv[:, C:], new_qkv[:, C:])  # k,v not
        # untouched modules are shared, not copied
        assert merged["unet"]["mid_res_0"] is params["unet"]["mid_res_0"]

    def test_weight_zero_is_identity(self):
        params = init_params(TINY)
        merged, _, _ = lora_mod.merge_lora(params, make_lora_sd(), 0.0, TINY)
        a = params["unet"]["down_0_attn_0"]["block_0"]["attn1"]["qkv"]["kernel"]
        b = merged["unet"]["down_0_attn_0"]["block_0"]["attn1"]["qkv"]["kernel"]
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_unknown_modules_skipped(self):
        sd = {"lora_unet_bogus_module.lora_down.weight":
              np.zeros((4, 8), np.float32),
              "lora_unet_bogus_module.lora_up.weight":
              np.zeros((8, 4), np.float32)}
        _, applied, skipped = lora_mod.merge_lora(
            init_params(TINY), sd, 1.0, TINY)
        assert applied == 0 and skipped == 1


class TestLoraPromptSyntax:
    def test_extract_tags(self):
        clean, tags = lora_mod.extract_lora_tags(
            "a cow <lora:style:0.8> in a field <lora:detail> end")
        assert clean == "a cow in a field end"
        assert tags == [("style", 0.8, 0.8), ("detail", 1.0, 1.0)]

    def test_extract_dual_weight_tag(self):
        # webui dual-multiplier form: <lora:name:unet_w:te_w>
        clean, tags = lora_mod.extract_lora_tags("x <lora:s:0.5:0.7> y")
        assert clean == "x y"
        assert tags == [("s", 0.5, 0.7)]

    def test_engine_end_to_end(self):
        params = init_params(TINY)
        loras = {"test": make_lora_sd(scale=2.0)}
        eng = Engine(TINY, params, chunk_size=4, state=GenerationState(),
                     lora_provider=loras.get)
        base = eng.txt2img(GenerationPayload(
            prompt="a cow", steps=4, width=32, height=32, seed=3))
        styled = eng.txt2img(GenerationPayload(
            prompt="a cow <lora:test:1.0>", steps=4, width=32, height=32,
            seed=3))
        assert styled.images[0] != base.images[0]
        # infotext keeps the tag so the image round-trips (webui convention)
        assert "<lora:test:1.0>" in styled.infotexts[0]
        # deactivation restores the base outputs exactly
        again = eng.txt2img(GenerationPayload(
            prompt="a cow", steps=4, width=32, height=32, seed=3))
        assert again.images[0] == base.images[0]

    def test_partial_resolve_never_leaks_into_tagless_request(self):
        # Regression (advisor r2, medium): an adapter set where one tag
        # fails to resolve must NOT leave partially-merged params latched —
        # the very next tag-less request has to render from pristine base.
        params = init_params(TINY)
        loras = {"good": make_lora_sd(scale=2.0)}
        eng = Engine(TINY, params, chunk_size=4, state=GenerationState(),
                     lora_provider=loras.get)
        base = eng.txt2img(GenerationPayload(
            prompt="a cow", steps=4, width=32, height=32, seed=3))
        # 'good' merges, 'nope' fails -> unresolved set, params are dirty
        eng.txt2img(GenerationPayload(
            prompt="a cow <lora:good:1.0> <lora:nope:1.0>", steps=4,
            width=32, height=32, seed=3))
        clean = eng.txt2img(GenerationPayload(
            prompt="a cow", steps=4, width=32, height=32, seed=3))
        assert clean.images[0] == base.images[0]

    def test_missing_lora_warns_and_continues(self):
        eng = Engine(TINY, init_params(TINY), chunk_size=4,
                     state=GenerationState(), lora_provider=lambda n: None)
        r = eng.txt2img(GenerationPayload(
            prompt="x <lora:nope:1.0>", steps=2, width=32, height=32, seed=1))
        assert len(r.images) == 1


class TestInpaintPreprocessor:
    def test_masked_pixels_become_minus_one(self):
        img = np.full((8, 8, 3), 128, np.uint8)
        mask = np.zeros((8, 8), np.uint8)
        mask[2:4, 2:4] = 255
        out = run_preprocessor("inpaint", img, mask=mask)
        np.testing.assert_allclose(out[2:4, 2:4], -1.0)
        np.testing.assert_allclose(out[0, 0], 128 / 255.0, rtol=1e-6)
        # mask-less call degrades to plain normalization
        plain = run_preprocessor("inpaint_only", img)
        np.testing.assert_allclose(plain, 128 / 255.0, rtol=1e-6)

    def test_engine_parses_mikubill_mask_channel(self):
        eng = Engine(TINY, init_params(TINY), chunk_size=4,
                     state=GenerationState(),
                     controlnet_provider=lambda name: None)
        mask = np.zeros((16, 16), np.uint8)
        mask[:8] = 255
        payload = GenerationPayload(
            prompt="x", steps=2, width=32, height=32, seed=1,
            alwayson_scripts={"controlnet": {"args": [{
                "enabled": True,
                "image": {"image": array_to_b64png(
                    np.full((16, 16, 3), 200, np.uint8)),
                    "mask": array_to_b64png(mask)},
                "module": "inpaint", "model": "inp"}]}})
        units = eng._parse_controlnet_units(payload)
        assert len(units) == 1 and units[0]["mask"] is not None


def make_ldm_controlnet(cfg, prefix="control_model"):
    """Synthetic ldm ControlNet state dict for the TINY unet config."""
    sd = {}
    ch0 = cfg.block_out_channels[0]
    tdim = 4 * ch0
    ctx = cfg.cross_attention_dim
    _lin(sd, f"{prefix}.time_embed.0", tdim, ch0)
    _lin(sd, f"{prefix}.time_embed.2", tdim, tdim)
    _conv(sd, f"{prefix}.input_blocks.0.0", ch0, cfg.in_channels)
    hint_chs = (16, 16, 32, 32, 96, 96, 256)
    prev = 3
    for i, ch in enumerate(hint_chs):
        _conv(sd, f"{prefix}.input_hint_block.{2 * i}", ch, prev)
        prev = ch
    _conv(sd, f"{prefix}.input_hint_block.{2 * len(hint_chs)}", ch0, prev)

    levels = list(zip(cfg.block_out_channels, cfg.down_blocks))
    _conv(sd, f"{prefix}.zero_convs.0.0", ch0, ch0, k=1)
    n = 1
    prev = ch0
    for level, (ch, depth) in enumerate(levels):
        for i in range(cfg.layers_per_block):
            _ldm_res(sd, f"{prefix}.input_blocks.{n}.0", prev, ch, tdim)
            if depth is not None:
                _ldm_xformer(sd, f"{prefix}.input_blocks.{n}.1", ch, depth,
                             ctx)
            _conv(sd, f"{prefix}.zero_convs.{n}.0", ch, ch, k=1)
            prev = ch
            n += 1
        if level < len(levels) - 1:
            _conv(sd, f"{prefix}.input_blocks.{n}.0.op", ch, ch)
            _conv(sd, f"{prefix}.zero_convs.{n}.0", ch, ch, k=1)
            n += 1
    mid = cfg.block_out_channels[-1]
    _ldm_res(sd, f"{prefix}.middle_block.0", mid, mid, tdim)
    _ldm_xformer(sd, f"{prefix}.middle_block.1", mid, cfg.mid_block_depth,
                 ctx)
    _ldm_res(sd, f"{prefix}.middle_block.2", mid, mid, tdim)
    _conv(sd, f"{prefix}.middle_block_out.0", mid, mid, k=1)
    return sd


class TestControlNet:
    def test_conversion_matches_init(self):
        cfg = TINY.unet
        sd = make_ldm_controlnet(cfg)
        converted = convert_controlnet(sd, cfg)
        model = ControlNet(cfg)
        lat = jnp.zeros((1, 8, 8, 4))
        hint = jnp.zeros((1, 64, 64, 3))  # hint embedder downsamples x8
        init = model.init(jax.random.key(0), lat, jnp.ones((1,)),
                          jnp.zeros((1, 77, cfg.cross_attention_dim)),
                          hint)["params"]
        from test_models import assert_same_structure

        assert_same_structure(converted, init, "controlnet")
        res = model.apply({"params": converted}, lat, jnp.ones((1,)),
                          jnp.zeros((1, 77, cfg.cross_attention_dim)), hint)
        assert len(res) > 2
        assert all(np.isfinite(np.asarray(r)).all() for r in res)

    def test_zero_init_controlnet_is_identity(self):
        """A freshly initialized ControlNet has zero output convs, so its
        residuals are zero and generation must be bit-identical to running
        with no unit at all."""
        params = init_params(TINY)
        cfg = TINY.unet
        model = ControlNet(cfg)
        cn_params = model.init(
            jax.random.key(1), jnp.zeros((1, 8, 8, 4)), jnp.ones((1,)),
            jnp.zeros((1, 77, cfg.cross_attention_dim)),
            jnp.zeros((1, 64, 64, 3)))["params"]
        eng = Engine(TINY, params, chunk_size=4, state=GenerationState(),
                     controlnet_provider=lambda n: cn_params)
        plain = eng.txt2img(GenerationPayload(
            prompt="c", steps=3, width=32, height=32, seed=5))
        hint_img = np.zeros((32, 32, 3), np.uint8)
        with_cn = eng.txt2img(GenerationPayload(
            prompt="c", steps=3, width=32, height=32, seed=5,
            alwayson_scripts={"controlnet": {"args": [{
                "enabled": True, "image": array_to_b64png(hint_img),
                "module": "none", "model": "zero", "weight": 1.0,
            }]}}))
        assert with_cn.images[0] == plain.images[0]

    def test_trained_controlnet_changes_output(self):
        params = init_params(TINY)
        cfg = TINY.unet
        converted = convert_controlnet(make_ldm_controlnet(cfg), cfg)
        eng = Engine(TINY, params, chunk_size=4, state=GenerationState(),
                     controlnet_provider=lambda n: converted)
        plain = eng.txt2img(GenerationPayload(
            prompt="c", steps=3, width=32, height=32, seed=5))
        hint_img = (RNG.random((32, 32, 3)) * 255).astype(np.uint8)
        unit = {"enabled": True, "image": array_to_b64png(hint_img),
                "module": "none", "model": "cn", "weight": 1.0}
        with_cn = eng.txt2img(GenerationPayload(
            prompt="c", steps=3, width=32, height=32, seed=5,
            alwayson_scripts={"controlnet": {"args": [unit]}}))
        assert with_cn.images[0] != plain.images[0]
        # weight 0 gates the residuals off entirely
        off = eng.txt2img(GenerationPayload(
            prompt="c", steps=3, width=32, height=32, seed=5,
            alwayson_scripts={"controlnet": {"args": [
                {**unit, "weight": 0.0}]}}))
        assert off.images[0] == plain.images[0]


class TestPreprocessors:
    def test_canny_finds_edges(self):
        img = np.zeros((64, 64, 3), np.uint8)
        img[:, 32:] = 255  # vertical edge at x=32
        edges = preprocess_canny(img)
        assert edges.shape == (64, 64, 3)
        assert edges[:, 30:34].max() == 1.0   # edge detected
        assert edges[:, :28].max() == 0.0     # flat region clean
        assert edges[:, 36:].max() == 0.0

    def test_unknown_module_falls_back(self):
        img = np.full((8, 8, 3), 128, np.uint8)
        out = run_preprocessor("mystery-module", img)
        np.testing.assert_allclose(out, 128 / 255.0, atol=1e-6)


class TestAdaptiveGuidanceWindows:
    """DPM adaptive gates ControlNet units host-side per attempt from
    log-sigma progress (engine._denoise_adaptive controls_at; VERDICT r4
    item 4) — a windowed unit must actually change behavior vs the old
    whole-run widening."""

    def _engine(self):
        params = init_params(TINY)
        cfg = TINY.unet
        converted = convert_controlnet(make_ldm_controlnet(cfg), cfg)
        return Engine(TINY, params, chunk_size=4, state=GenerationState(),
                      controlnet_provider=lambda n: converted)

    def _run(self, eng, **unit_overrides):
        hint_img = (RNG.random((32, 32, 3)) * 255).astype(np.uint8)
        unit = {"enabled": True, "image": array_to_b64png(hint_img),
                "module": "none", "model": "cn", "weight": 1.0,
                **unit_overrides}
        return eng.txt2img(GenerationPayload(
            prompt="c", steps=6, width=32, height=32, seed=5,
            sampler_name="DPM adaptive",
            alwayson_scripts={"controlnet": {"args": [unit]}}))

    def test_window_gates_unit(self):
        eng = self._engine()
        full = self._run(eng)
        early_only = self._run(eng, guidance_start=0.0, guidance_end=0.15)
        # the unit must be inactive for most of the trajectory — different
        # pixels than the full-window run (the pre-fix widening made these
        # byte-identical)
        assert early_only.images[0] != full.images[0]

    def test_zero_width_window_equals_no_unit(self):
        eng = self._engine()
        plain = eng.txt2img(GenerationPayload(
            prompt="c", steps=6, width=32, height=32, seed=5,
            sampler_name="DPM adaptive"))
        # window that can never contain any fraction > its end at start 1.0
        never = self._run(eng, guidance_start=0.999, guidance_end=0.9991)
        assert never.images[0] == plain.images[0]

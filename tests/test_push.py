"""Push control plane tests (obs/push.py, obs/fleetlog.py).

Covers the streaming-delta plane end to end:

- DeltaBuffer cursor semantics — assignment, bounded eviction with
  ``lost`` accounting, the journaled ``push_buffer_evicted`` trail,
  and the long-poll fast path;
- DeltaSubscriber resilience — disconnect mid-stream then cursor
  resume with zero loss and zero duplicates, redelivered-batch dedup,
  slow-consumer loss surfaced in status, and the 404 demotion to the
  poll prober's own fetch+digest (``push_fallback`` journaled);
- fleet journal merge (obs/fleetlog.py) — seq dedup on redelivery,
  per-node monotonic ``t_fleet`` clamping, causal-order violation
  detection, request-id filtering, and bounded per-node buffers;
- severity-routed notify — ``channel_for`` precedence, the two-hook
  delivery matrix (page lands on url1 only, warn on url2 only),
  tenant-scoped overrides, and the ``notify_dropped`` overflow trail;
- the HTTP surface — ``/internal/deltas`` (404 when gated off, 422 on
  a bad cursor), ``/internal/push``, ``/internal/fleet/timeline``,
  and a real-HTTP subscriber round-trip including the fallback;
- the tools — ``fed_report --timeline`` exit codes and rendering,
  ``replay --fleet`` cross-node journey reconstruction;
- the gate-off golden: with SDTPU_PUSH unset the serving path pins to
  the *same* hash as the poll-only build ("serving/federation-off-
  default") and no push/fleetlog state leaks;
- the acceptance e2e: two real in-process HTTP workers, chaos-kill
  one, and a single GET /internal/fleet/timeline response tells the
  whole story — the victim's last events, the fault injection, the
  stale alert firing with its severity, and the requeue landing on
  the healthy worker — with zero causal violations and zero event
  loss.
"""

import json
import sys
import threading
import time
import types
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

sys.path.insert(0, "tools")

from stable_diffusion_webui_distributed_tpu.obs import alerts as obs_alerts
from stable_diffusion_webui_distributed_tpu.obs import (
    federation as obs_fed,
)
from stable_diffusion_webui_distributed_tpu.obs import (
    fleetlog as obs_fleetlog,
)
from stable_diffusion_webui_distributed_tpu.obs import journal as obs_journal
from stable_diffusion_webui_distributed_tpu.obs import notify as obs_notify
from stable_diffusion_webui_distributed_tpu.obs import (
    prometheus as _obs_prom,
)
from stable_diffusion_webui_distributed_tpu.obs import push as obs_push
from stable_diffusion_webui_distributed_tpu.obs import tsdb as obs_tsdb
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.serving.bucketer import (
    ShapeBucketer,
)
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (
    ServingDispatcher,
)

from test_federation import (
    _METRICS_A,
    _TSDB_A,
    FakeBackend,
    FakeClock,
    FakeWorker,
    scripted_clock,
)
from test_goldens import _check
from test_pipeline import init_params


@pytest.fixture(autouse=True)
def _worker_counters_isolated():
    # The worker counters are process-global and accumulate across test
    # modules; a nonzero initial total legitimately ships as a delta
    # entry (that's production behavior), which would shift every
    # cursor number this module pins. Start each test from zero.
    for c in _obs_prom.WORKER_COUNTERS.values():
        c.clear()


@pytest.fixture()
def push_on(monkeypatch):
    monkeypatch.setenv("SDTPU_PUSH", "1")
    yield
    obs_push.reset()
    obs_fleetlog.reset()


@pytest.fixture()
def journal_on(monkeypatch):
    monkeypatch.setenv("SDTPU_JOURNAL", "1")
    obs_journal.JOURNAL.clear()
    yield
    obs_journal.JOURNAL.clear()


class SeamBackend:
    """In-process push_fetch seam over a DeltaBuffer; call numbers in
    ``fail_on`` raise (a disconnect mid-stream)."""

    def __init__(self, buf, fail_on=()):
        self.buf = buf
        self.calls = 0
        self.fail_on = set(fail_on)

    def push_fetch(self, cursor):
        self.calls += 1
        if self.calls in self.fail_on:
            raise ConnectionError("mid-stream disconnect")
        return self.buf.collect(cursor, hold_s=0.0)


class CannedBackend:
    """push_fetch returning the same canned document every time."""

    def __init__(self, doc):
        self.doc = doc
        self.calls = 0

    def push_fetch(self, cursor):
        self.calls += 1
        return json.loads(json.dumps(self.doc))


# -- knobs --------------------------------------------------------------------

class TestKnobs:
    def test_gate_defaults_off(self, monkeypatch):
        monkeypatch.delenv("SDTPU_PUSH", raising=False)
        assert obs_push.enabled() is False
        monkeypatch.setenv("SDTPU_PUSH", "1")
        assert obs_push.enabled() is True

    def test_cursor_buf_default_and_floor(self, monkeypatch):
        monkeypatch.delenv("SDTPU_PUSH_CURSOR_BUF", raising=False)
        assert obs_push.cursor_buf() == 1024
        monkeypatch.setenv("SDTPU_PUSH_CURSOR_BUF", "2")
        assert obs_push.cursor_buf() == 16

    def test_wait_default_and_floor(self, monkeypatch):
        monkeypatch.delenv("SDTPU_PUSH_WAIT_S", raising=False)
        assert obs_push.wait_s() == obs_push.DEFAULT_WAIT_S
        monkeypatch.setenv("SDTPU_PUSH_WAIT_S", "-3")
        assert obs_push.wait_s() == 0.0


# -- worker-side buffer -------------------------------------------------------

class TestDeltaBuffer:
    def test_cursors_are_assigned_monotonically(self):
        buf = obs_push.DeltaBuffer(capacity=16)
        for i in range(3):
            assert buf.publish("sample", {"name": "s", "t": i,
                                          "v": 1.0}) == 0
        doc = buf.collect(0, hold_s=0.0)
        assert [e["cursor"] for e in doc["entries"]] == [1, 2, 3]
        assert doc["next_cursor"] == 3
        assert doc["lost"] == 0
        # resume after the last cursor sees nothing
        assert buf.collect(3, hold_s=0.0)["entries"] == []

    def test_eviction_counts_and_reports_lost(self):
        buf = obs_push.DeltaBuffer(capacity=4)
        for i in range(10):
            buf.publish("sample", {"name": "s", "t": i, "v": 1.0})
        assert buf.stats() == {"retained": 4, "next_cursor": 10,
                               "evicted_total": 6}
        doc = buf.collect(0, hold_s=0.0)
        # entries 1..6 evicted: a cursor-0 consumer lost exactly those
        assert doc["lost"] == 6
        assert [e["cursor"] for e in doc["entries"]] == [7, 8, 9, 10]
        # a consumer inside the retained window lost nothing
        assert buf.collect(7, hold_s=0.0)["lost"] == 0

    def test_ingest_pulls_journal_events_once(self, journal_on):
        buf = obs_push.DeltaBuffer(capacity=64)
        obs_journal.emit("push_fallback", "rid-a", worker="a")
        obs_journal.emit("push_fallback", "rid-b", worker="b")
        assert buf.ingest() == 2
        assert buf.ingest() == 0  # position advanced; no re-ship
        doc = buf.collect(0, hold_s=0.0)
        kinds = {e["kind"] for e in doc["entries"]}
        assert kinds == {"journal"}
        workers = [e["event"]["attrs"]["worker"] for e in doc["entries"]]
        assert workers == ["a", "b"]

    def test_ingest_eviction_is_journaled(self, journal_on):
        buf = obs_push.DeltaBuffer(capacity=4)
        for i in range(10):
            obs_journal.emit("push_fallback", f"rid-{i}", worker="w")
        assert buf.ingest() == 10
        assert buf.stats()["evicted_total"] == 6
        events = obs_journal.JOURNAL.events_for("push-buffer")
        assert any(e["event"] == "push_buffer_evicted"
                   and e["attrs"]["evicted"] == 6 for e in events)

    def test_long_poll_returns_immediately_with_entries(self):
        buf = obs_push.DeltaBuffer(capacity=16)
        buf.publish("sample", {"name": "s", "t": 0.0, "v": 1.0})
        t0 = time.monotonic()
        doc = buf.collect(0, hold_s=5.0)
        assert time.monotonic() - t0 < 1.0
        assert len(doc["entries"]) == 1

    def test_clear_resets_cursor_space(self):
        buf = obs_push.DeltaBuffer(capacity=16)
        buf.publish("sample", {"name": "s", "t": 0.0, "v": 1.0})
        buf.clear()
        assert buf.stats() == {"retained": 0, "next_cursor": 0,
                               "evicted_total": 0}


# -- master-side subscriber ---------------------------------------------------

class TestDeltaSubscriber:
    def test_disconnect_then_resume_zero_loss_zero_dup(self):
        buf = obs_push.DeltaBuffer(capacity=1024)
        backend = SeamBackend(buf, fail_on={2})
        store = obs_tsdb.SeriesStore(points=64)
        sub = obs_push.DeltaSubscriber("w", backend, store=store,
                                       clock=FakeClock(10.0))
        for i in range(3):
            buf.publish("sample", {"name": "queue_wait_p95_s",
                                   "t": float(i), "v": 0.1 * i})
        assert sub.poll_once(now=10.0) == 3
        assert sub.cursor == 3
        for i in range(2):
            buf.publish("sample", {"name": "queue_wait_p95_s",
                                   "t": 10.0 + i, "v": 0.5})
        # the disconnect: nothing applied, failure bookkept, staleness
        # series still records (the alert input keeps flowing)
        assert sub.poll_once(now=11.0) == 0
        st = sub.status()
        assert st["failures"] == 1
        assert st["mode"] == "push"
        assert store.latest("worker:w/staleness_s") is not None
        # the resume: exactly the two new entries, nothing twice
        assert sub.poll_once(now=12.0) == 2
        st = sub.status()
        assert st["applied"] == 5
        assert st["duplicates"] == 0
        assert st["lost"] == 0
        assert st["cursor"] == 5
        assert st["last_error"] is None

    def test_redelivered_batch_is_deduped(self):
        entries = [{"cursor": i, "kind": "sample",
                    "name": "queue_wait_p95_s", "t": float(i), "v": 1.0}
                   for i in (1, 2, 3)]
        doc = {"enabled": True, "next_cursor": 3, "evicted_total": 0,
               "lost": 0, "clock_us": 0.0, "entries": entries}
        sub = obs_push.DeltaSubscriber(
            "w", CannedBackend(doc), store=obs_tsdb.SeriesStore(points=64),
            clock=FakeClock(5.0))
        assert sub.poll_once(now=5.0) == 3
        assert sub.poll_once(now=6.0) == 0  # the whole batch again
        st = sub.status()
        assert st["applied"] == 3
        assert st["duplicates"] == 3
        assert st["cursor"] == 3

    def test_slow_consumer_loss_is_accounted(self):
        buf = obs_push.DeltaBuffer(capacity=4)
        for i in range(10):
            buf.publish("sample", {"name": "queue_wait_p95_s",
                                   "t": float(i), "v": 1.0})
        sub = obs_push.DeltaSubscriber(
            "w", SeamBackend(buf), store=obs_tsdb.SeriesStore(points=64),
            clock=FakeClock(5.0))
        assert sub.poll_once(now=5.0) == 4
        st = sub.status()
        assert st["lost"] == 6
        assert st["cursor"] == 10

    def test_counter_entries_become_error_rate(self):
        entries = [
            {"cursor": 1, "kind": "counter", "name": "requests_total",
             "total": 4.0},
            {"cursor": 2, "kind": "counter", "name": "failures_total",
             "total": 1.0},
        ]
        doc = {"enabled": True, "next_cursor": 2, "evicted_total": 0,
               "lost": 0, "clock_us": 0.0, "entries": entries}
        store = obs_tsdb.SeriesStore(points=64)
        sub = obs_push.DeltaSubscriber("w", CannedBackend(doc),
                                       store=store, clock=FakeClock(5.0))
        sub.poll_once(now=5.0)
        assert store.latest("worker:w/requests_total")[1] == 4.0
        assert store.latest("worker:w/failures_total")[1] == 1.0
        assert store.latest("worker:w/error_rate")[1] == \
            pytest.approx(0.25)
        # the p95 defaults rather than going absent (prober parity)
        assert store.latest("worker:w/queue_wait_p95_s")[1] == 0.0

    def test_remote_samples_never_land_in_the_future(self):
        # remote clock way ahead: offset correction would place the
        # sample past the master's now — it must clamp to now
        entries = [{"cursor": 1, "kind": "sample",
                    "name": "queue_wait_p95_s", "t": 500.0, "v": 2.0}]
        doc = {"enabled": True, "next_cursor": 1, "evicted_total": 0,
               "lost": 0, "clock_us": 100.0 * 1e6, "entries": entries}
        store = obs_tsdb.SeriesStore(points=64)
        sub = obs_push.DeltaSubscriber(
            "w", CannedBackend(doc), store=store,
            clock=scripted_clock([100.0, 100.0], 100.0))
        sub.poll_once(now=100.0)
        t, v = store.latest("worker:w/queue_wait_p95_s")
        assert v == 2.0
        assert t <= 100.0

    def test_staleness_anchors_to_the_rtt_midpoint(self):
        buf = obs_push.DeltaBuffer(capacity=16)
        buf.publish("sample", {"name": "queue_wait_p95_s", "t": 0.0,
                               "v": 1.0})
        store = obs_tsdb.SeriesStore(points=64)
        sub = obs_push.DeltaSubscriber(
            "w", SeamBackend(buf), store=store,
            clock=scripted_clock([100.0, 102.0], 102.0))
        sub.poll_once(now=102.0)
        assert store.latest("worker:w/staleness_s")[1] == \
            pytest.approx(1.0)
        assert store.latest("worker:w/poll_rtt_s")[1] == pytest.approx(2.0)

    def test_404_demotes_to_poll_fallback(self, journal_on):
        class LegacyBackend(FakeBackend):
            """Predates /internal/deltas: 404 on push, answers polls."""

            def __init__(self):
                super().__init__(_METRICS_A, _TSDB_A)
                self.push_calls = 0

            def push_fetch(self, cursor):
                self.push_calls += 1
                raise obs_push._HTTPStatusError(404, "HTTP 404: not found")

        backend = LegacyBackend()
        store = obs_tsdb.SeriesStore(points=64)
        sub = obs_push.DeltaSubscriber("w", backend, store=store,
                                       clock=FakeClock(10.0))
        assert sub.poll_once(now=10.0) > 0  # the fallback scrape landed
        st = sub.status()
        assert st["mode"] == "poll"
        assert st["fallbacks"] == 1
        # the prober's own digest filled the same series
        assert store.latest("worker:w/requests_total")[1] == 4.0
        assert store.latest("worker:w/error_rate")[1] == \
            pytest.approx(0.25)
        assert store.latest("worker:w/queue_wait_p95_s")[1] == 0.5
        events = obs_journal.JOURNAL.events_for("push-w")
        assert any(e["event"] == "push_fallback"
                   and e["attrs"]["worker"] == "w" for e in events)
        # once demoted it never re-knocks on the push endpoint
        sub.poll_once(now=11.0)
        assert backend.push_calls == 1

    def test_journal_entries_stream_into_the_fleetlog(self, journal_on):
        obs_fleetlog.reset()
        ev = {"seq": 1, "event": "push_fallback", "request_id": "r1",
              "t_mono": 50.0, "parent": None, "attrs": {"worker": "w"}}
        doc = {"enabled": True, "next_cursor": 1, "evicted_total": 0,
               "lost": 0, "clock_us": 100.0 * 1e6,
               "entries": [{"cursor": 1, "kind": "journal", "event": ev}]}
        sub = obs_push.DeltaSubscriber(
            "w", CannedBackend(doc), store=obs_tsdb.SeriesStore(points=64),
            clock=scripted_clock([100.0, 100.0], 100.0))
        try:
            sub.poll_once(now=100.0)
            rows = [r for r in obs_fleetlog.LOG.merged()
                    if r["node"] == "w"]
            assert len(rows) == 1
            # offset = midpoint(100) - remote clock(100) = 0: the
            # remote t_mono lands unchanged on the fleet axis
            assert rows[0]["t_fleet"] == pytest.approx(50.0)
            assert rows[0]["request_id"] == "r1"
        finally:
            obs_fleetlog.reset()


# -- the manager --------------------------------------------------------------

class TestPushManager:
    def test_gate_off_tick_is_a_noop(self, monkeypatch):
        monkeypatch.delenv("SDTPU_PUSH", raising=False)
        mgr = obs_push.PushManager(store=obs_tsdb.SeriesStore(points=64))
        mgr.set_source([FakeWorker("a", FakeBackend(_METRICS_A))])
        assert mgr.tick() == 0
        assert mgr.start() is False
        assert mgr.summary()["workers"] == {}

    def test_tick_streams_and_aggregates(self, push_on, monkeypatch):
        from stable_diffusion_webui_distributed_tpu.obs import (
            prometheus as obs_prom,
        )

        monkeypatch.setattr(obs_prom, "fleet_queue_wait_p95", lambda: 0.0)
        buf = obs_push.DeltaBuffer(capacity=64)
        buf.publish("counter", {"name": "requests_total", "total": 10.0})
        buf.publish("counter", {"name": "failures_total", "total": 1.0})
        store = obs_tsdb.SeriesStore(points=64)
        mgr = obs_push.PushManager(store=store, clock=FakeClock(10.0))
        mgr.set_source([FakeWorker("a", SeamBackend(buf))])
        assert mgr.tick(now=10.0) == 2
        assert store.latest("worker:a/error_rate")[1] == pytest.approx(0.1)
        assert store.latest("fleet/error_rate")[1] == pytest.approx(0.1)
        assert store.latest("fleet/worker_stale_count")[1] == 0.0
        assert store.latest("fleet/poll_failures_total")[1] == 0.0
        doc = mgr.summary()
        assert doc["workers"]["a"]["mode"] == "push"
        assert doc["event_loss"] == 0
        assert doc["duplicates"] == 0

    def test_unreached_worker_counts_fully_errored(self, push_on,
                                                   monkeypatch):
        from stable_diffusion_webui_distributed_tpu.obs import (
            prometheus as obs_prom,
        )

        monkeypatch.setattr(obs_prom, "fleet_queue_wait_p95", lambda: 0.0)

        class DeadBackend:
            def push_fetch(self, cursor):
                raise ConnectionError("gone")

        store = obs_tsdb.SeriesStore(points=64)
        mgr = obs_push.PushManager(store=store, clock=FakeClock(10.0))
        mgr.set_source([FakeWorker("dead", DeadBackend())])
        mgr.tick(now=10.0)
        assert store.latest("fleet/error_rate")[1] == 1.0
        assert store.latest("fleet/poll_failures_total")[1] == 1.0

    def test_subscribers_follow_the_source(self, push_on):
        buf = obs_push.DeltaBuffer(capacity=16)
        mgr = obs_push.PushManager(store=obs_tsdb.SeriesStore(points=64),
                                   clock=FakeClock(0.0))
        workers = [FakeWorker("a", SeamBackend(buf))]
        mgr.set_source(workers)
        mgr.tick(now=0.0)
        assert set(mgr.summary()["workers"]) == {"a"}
        mgr.set_source([])
        mgr.tick(now=1.0)
        assert mgr.summary()["workers"] == {}


# -- fleet journal merge ------------------------------------------------------

def _ev(seq, event="push_fallback", rid="r", t=0.0, parent=None,
        attrs=None):
    return {"seq": seq, "event": event, "request_id": rid, "t_mono": t,
            "parent": parent, "attrs": attrs or {}}


class TestFleetLog:
    def test_redelivery_dedupes_by_seq(self):
        log = obs_fleetlog.FleetLog()
        batch = [_ev(1, t=1.0), _ev(2, t=2.0)]
        assert log.ingest("w", batch) == 2
        assert log.ingest("w", batch) == 0  # cursor-resumed redelivery
        assert log.stats()["deduped"] == 2
        assert log.nodes()["w"]["count"] == 2

    def test_t_fleet_clamps_monotonic_per_node(self):
        log = obs_fleetlog.FleetLog()
        log.ingest("w", [_ev(1, t=10.0)], offset_s=0.0)
        # a later, smaller offset estimate would re-order the node
        # against itself — the clamp holds seq order on the fleet axis
        log.ingest("w", [_ev(2, t=11.0)], offset_s=-5.0)
        rows = [r for r in log.merged() if r["node"] == "w"]
        assert [r["seq"] for r in rows] == [1, 2]
        assert rows[1]["t_fleet"] >= rows[0]["t_fleet"]
        assert obs_fleetlog.causal_violations(rows) == []

    def test_per_node_buffers_are_bounded(self, monkeypatch):
        monkeypatch.setenv("SDTPU_JOURNAL_MAX", "16")
        log = obs_fleetlog.FleetLog()
        log.ingest("w", [_ev(i, t=float(i)) for i in range(1, 21)])
        assert log.nodes()["w"]["count"] == 16
        assert log.stats()["evicted"] == 4
        # the oldest went first
        assert min(r["seq"] for r in log.merged()) == 5

    def test_causal_violation_detection(self):
        # hand-built inversion: seq 2's parent (seq 1, same node) is
        # placed after it on the merged axis
        events = [
            {"node": "w", "seq": 2, "event": "completed",
             "request_id": "r", "t_fleet": 1.0, "parent": 1},
            {"node": "w", "seq": 1, "event": "submitted",
             "request_id": "r", "t_fleet": 2.0, "parent": None},
        ]
        rows = obs_fleetlog.causal_violations(events)
        assert len(rows) == 1
        assert rows[0]["seq"] == 2
        assert rows[0]["parent"] == 1
        assert rows[0]["child_index"] == 0
        assert rows[0]["parent_index"] == 1

    def test_missing_parent_is_not_a_violation(self):
        events = [{"node": "w", "seq": 9, "event": "completed",
                   "request_id": "r", "t_fleet": 1.0, "parent": 3}]
        assert obs_fleetlog.causal_violations(events) == []

    def test_timeline_merges_local_and_streamed(self, journal_on):
        obs_fleetlog.reset()
        try:
            obs_journal.emit("push_fallback", "r1", worker="local-side")
            obs_fleetlog.ingest("w", [_ev(1, rid="r1", t=1.0),
                                      _ev(2, rid="r2", t=2.0)])
            doc = obs_fleetlog.timeline()
            assert set(doc) == {"enabled", "nodes", "count", "violations",
                                "violation_rows", "events"}
            assert doc["enabled"] is True
            nodes = {e["node"] for e in doc["events"]}
            assert nodes == {"local", "w"}
            # the request-id filter returns the one cross-node story
            filtered = obs_fleetlog.timeline(request_id="r1")
            assert {e["node"] for e in filtered["events"]} == \
                {"local", "w"}
            assert all(e["request_id"] == "r1"
                       for e in filtered["events"])
        finally:
            obs_fleetlog.reset()


# -- severity-routed notify ---------------------------------------------------

def _hook_server():
    """One local webhook capture server; returns (url, received, close)."""
    received = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            received.append(json.loads(self.rfile.read(n) or b"{}"))
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"ok")

        def log_message(self, *args):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    def close():
        srv.shutdown()
        srv.server_close()

    return f"http://127.0.0.1:{srv.server_address[1]}/hook", received, close


class TestSeverityRouting:
    def test_channel_for_precedence(self, monkeypatch):
        monkeypatch.setenv("SDTPU_NOTIFY_ROUTES",
                           "page=http://p,warn=http://w,"
                           "acme:page=http://tenant")
        monkeypatch.setenv("SDTPU_NOTIFY_URL", "http://default")
        assert obs_notify.channel_for("page") == ("page", "http://p")
        assert obs_notify.channel_for("page", tenant="acme") == \
            ("acme:page", "http://tenant")
        assert obs_notify.channel_for("page", tenant="other") == \
            ("page", "http://p")
        # unrouted severity falls to the default channel...
        assert obs_notify.channel_for("info") == \
            ("default", "http://default")
        monkeypatch.delenv("SDTPU_NOTIFY_URL", raising=False)
        # ...and to None with no default configured
        assert obs_notify.channel_for("info") is None

    def test_malformed_route_entries_are_skipped(self, monkeypatch):
        monkeypatch.setenv("SDTPU_NOTIFY_ROUTES",
                           "page=http://p,, =x,broken,warn= ,=http://y")
        assert obs_notify.routes() == {"page": "http://p"}

    def test_delivery_matrix_page_and_warn_never_cross(self, monkeypatch):
        url1, page_hits, close1 = _hook_server()
        url2, warn_hits, close2 = _hook_server()
        monkeypatch.setenv("SDTPU_NOTIFY_ROUTES",
                           f"page={url1},warn={url2}")
        monkeypatch.delenv("SDTPU_NOTIFY_URL", raising=False)
        monkeypatch.setenv("SDTPU_NOTIFY_DEDUP_S", "60")
        n = obs_notify.Notifier()
        try:
            assert n.notify_transition("r-page", "firing", 1.0, "d",
                                       severity="page") is True
            assert n.notify_transition("r-warn", "firing", 1.0, "d",
                                       severity="warn") is True
            # info has no route and no default: rejected at the door
            assert n.notify_transition("r-info", "firing", 1.0, "d",
                                       severity="info") is False
            assert n.flush(5.0) is True
            assert [b["rule"] for b in page_hits] == ["r-page"]
            assert [b["rule"] for b in warn_hits] == ["r-warn"]
            per = n.counts_by_channel()
            assert per["page"] == {"sent": 1}
            assert per["warn"] == {"sent": 1}
            assert "info" not in per
        finally:
            n.stop()
            close1()
            close2()

    def test_tenant_override_wins_the_route(self, monkeypatch):
        url1, fleet_hits, close1 = _hook_server()
        url2, tenant_hits, close2 = _hook_server()
        monkeypatch.setenv("SDTPU_NOTIFY_ROUTES",
                           f"page={url1},acme:page={url2}")
        monkeypatch.delenv("SDTPU_NOTIFY_URL", raising=False)
        n = obs_notify.Notifier()
        try:
            assert n.notify_transition("r", "firing", 1.0, "d",
                                       severity="page",
                                       tenant="acme") is True
            assert n.flush(5.0) is True
            assert [b["rule"] for b in tenant_hits] == ["r"]
            assert fleet_hits == []
            assert n.counts_by_channel()["acme:page"] == {"sent": 1}
        finally:
            n.stop()
            close1()
            close2()

    def test_overflow_drops_newest_and_journals(self, monkeypatch,
                                                journal_on):
        url, _hits, close = _hook_server()
        monkeypatch.setenv("SDTPU_NOTIFY_ROUTES", f"page={url}")
        monkeypatch.delenv("SDTPU_NOTIFY_URL", raising=False)
        monkeypatch.setenv("SDTPU_NOTIFY_DEDUP_S", "0")
        n = obs_notify.Notifier()
        # stall the drain: the queue must actually fill
        monkeypatch.setattr(n._daemon, "start", lambda: None)
        try:
            for i in range(obs_notify._MAX_QUEUE + 1):
                n.notify_transition(f"r{i}", "firing", 1.0, "d",
                                    severity="page")
            doc = n.summary()
            assert doc["dropped"] == 1
            assert doc["queued"] == obs_notify._MAX_QUEUE
            dropped = [e for e in obs_journal.JOURNAL.snapshot()["events"]
                       if e["event"] == "notify_dropped"]
            assert len(dropped) == 1
            assert dropped[0]["attrs"]["channel"] == "page"
        finally:
            n.stop()
            close()


# -- the HTTP surface ---------------------------------------------------------

def _api_server():
    from stable_diffusion_webui_distributed_tpu.runtime.config import (
        ConfigModel,
    )
    from stable_diffusion_webui_distributed_tpu.scheduler.worker import (
        StubBackend,
        WorkerNode,
    )
    from stable_diffusion_webui_distributed_tpu.scheduler.world import (
        World,
    )
    from stable_diffusion_webui_distributed_tpu.server.api import ApiServer

    w = World(ConfigModel())
    w.add_worker(WorkerNode("m", StubBackend(), master=True, avg_ipm=10.0))
    return ApiServer(w, state=GenerationState(),
                     host="127.0.0.1", port=0).start()


def _get_json(port, path):
    url = f"http://127.0.0.1:{port}{path}"
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


class TestHTTPSurface:
    def test_deltas_404_when_gated_off(self, monkeypatch):
        monkeypatch.delenv("SDTPU_PUSH", raising=False)
        srv = _api_server()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_json(srv.port, "/internal/deltas?cursor=0")
            assert ei.value.code == 404
        finally:
            srv.stop()

    def test_deltas_serves_entries_and_validates(self, push_on,
                                                 monkeypatch):
        monkeypatch.setenv("SDTPU_PUSH_WAIT_S", "0")
        obs_push.BUFFER.clear()
        obs_push.BUFFER.publish("sample", {"name": "queue_wait_p95_s",
                                           "t": 1.0, "v": 0.5})
        srv = _api_server()
        try:
            doc = _get_json(srv.port, "/internal/deltas?cursor=0")
            assert doc["enabled"] is True
            assert doc["next_cursor"] >= 1
            assert any(e["kind"] == "sample" for e in doc["entries"])
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_json(srv.port, "/internal/deltas?cursor=bogus")
            assert ei.value.code == 422
        finally:
            srv.stop()

    def test_push_status_always_served(self, monkeypatch):
        monkeypatch.delenv("SDTPU_PUSH", raising=False)
        srv = _api_server()
        try:
            doc = _get_json(srv.port, "/internal/push")
            assert doc["enabled"] is False
            assert doc["workers"] == {}
            assert set(doc["buffer"]) == {"retained", "next_cursor",
                                          "evicted_total"}
        finally:
            srv.stop()

    def test_fleet_timeline_endpoint(self, journal_on):
        obs_fleetlog.reset()
        obs_fleetlog.ingest("w", [_ev(1, rid="r1", t=1.0),
                                  _ev(2, rid="r2", t=2.0)])
        srv = _api_server()
        try:
            doc = _get_json(srv.port, "/internal/fleet/timeline")
            assert doc["count"] >= 2
            assert "w" in doc["nodes"]
            filtered = _get_json(
                srv.port, "/internal/fleet/timeline?request_id=r1")
            assert all(e["request_id"] == "r1"
                       for e in filtered["events"])
        finally:
            srv.stop()
            obs_fleetlog.reset()

    def test_http_subscriber_roundtrip_and_fallback(self, push_on,
                                                    monkeypatch):
        monkeypatch.setenv("SDTPU_PUSH_WAIT_S", "0")
        obs_push.BUFFER.clear()
        obs_push.BUFFER.publish("counter", {"name": "requests_total",
                                            "total": 7.0})
        srv = _api_server()
        store = obs_tsdb.SeriesStore(points=64)
        try:
            backend = types.SimpleNamespace(
                address="127.0.0.1", port=srv.port, tls=False)
            sub = obs_push.DeltaSubscriber("m", backend, store=store)
            assert sub.poll_once() >= 1
            assert sub.status()["mode"] == "push"
            assert store.latest("worker:m/requests_total")[1] == 7.0
            # flip the worker's gate off mid-flight: the next knock is
            # a 404 and the subscriber polls the same node instead
            monkeypatch.delenv("SDTPU_PUSH", raising=False)
            assert sub.poll_once() >= 1
            assert sub.status()["mode"] == "poll"
            assert sub.status()["fallbacks"] == 1
            monkeypatch.setenv("SDTPU_PUSH", "1")
        finally:
            srv.stop()


# -- tools: fed_report --timeline, replay --fleet -----------------------------

def _timeline_doc(violation=False):
    events = [
        {"node": "local", "seq": 1, "event": "submitted",
         "request_id": "r1", "t_mono": 1.0, "t_fleet": 1.0,
         "parent": None, "attrs": {}},
        {"node": "victim", "seq": 1, "event": "job_failed",
         "request_id": "r1", "t_mono": 0.5, "t_fleet": 2.0,
         "parent": None, "attrs": {"worker": "victim"}},
        {"node": "local", "seq": 2, "event": "alert_firing",
         "request_id": "alert-worker_metrics_stale", "t_mono": 3.0,
         "t_fleet": 3.0, "parent": None,
         "attrs": {"rule": "worker_metrics_stale", "severity": "page"}},
        {"node": "local", "seq": 3, "event": "requeued",
         "request_id": "r1", "t_mono": 4.0, "t_fleet": 4.0,
         "parent": 1, "attrs": {"from_worker": "victim",
                                "to": ["alpha"], "recovered": 4,
                                "dropped": 0}},
        {"node": "alpha", "seq": 1, "event": "completed",
         "request_id": "r1", "t_mono": 2.0, "t_fleet": 5.0,
         "parent": None, "attrs": {}},
    ]
    if violation:
        # child placed before its same-node parent on the fleet axis
        events.insert(0, {"node": "victim", "seq": 2,
                          "event": "completed", "request_id": "r1",
                          "t_mono": 0.1, "t_fleet": 0.1, "parent": 1,
                          "attrs": {}})
    return {"enabled": True, "nodes": {}, "count": len(events),
            "violations": 0, "violation_rows": [], "events": events}


class TestFedReportTimeline:
    def test_build_and_render(self):
        import fed_report

        summary = fed_report.build_timeline(_timeline_doc())
        assert summary["kind"] == "timeline"
        assert summary["nodes"] == ["alpha", "local", "victim"]
        assert summary["violations"] == []
        text = fed_report.render_timeline(summary, color=False)
        assert "alert_firing" in text
        assert "[page]" in text
        assert "▲" in text
        colored = fed_report.render_timeline(summary, color=True)
        assert fed_report.SEV_COLORS["page"] in colored

    def test_violations_recomputed_not_trusted(self):
        import fed_report

        doc = _timeline_doc(violation=True)
        doc["violations"] = 0  # the tool must not trust the document
        summary = fed_report.build_timeline(doc)
        assert len(summary["violations"]) == 1
        assert summary["violations"][0]["node"] == "victim"

    def test_exit_codes(self, tmp_path, capsys):
        import fed_report

        clean = tmp_path / "clean.json"
        clean.write_text(json.dumps(_timeline_doc()))
        assert fed_report.main([str(clean), "--timeline",
                                "--no-color"]) == 0
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(_timeline_doc(violation=True)))
        assert fed_report.main([str(bad), "--timeline", "--json"]) == 1
        out = capsys.readouterr()
        assert "causal-order violation" in out.err
        not_timeline = tmp_path / "fleet.json"
        not_timeline.write_text(json.dumps({"workers": {}}))
        assert fed_report.main([str(not_timeline), "--timeline"]) == 2


class TestReplayFleet:
    def test_fleet_journey_reassembles_the_hops(self):
        import replay

        journey = replay.fleet_journey(_timeline_doc(), "r1")
        assert journey["events"] == 4  # the alert rides another rid
        assert journey["nodes"] == ["alpha", "local", "victim"]
        assert journey["hops"] == ["local", "victim", "local", "alpha"]
        assert len(journey["requeues"]) == 1
        assert journey["requeues"][0]["to"] == ["alpha"]
        assert journey["outcome"]["event"] == "completed"
        assert journey["outcome"]["node"] == "alpha"

    def test_main_fleet_mode(self, tmp_path, capsys):
        import replay

        path = tmp_path / "timeline.json"
        path.write_text(json.dumps(_timeline_doc()))
        assert replay.main(["--source", str(path), "--fleet",
                            "--request-id", "r1"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["hops"][0] == "local"
        assert replay.main(["--source", str(path), "--fleet",
                            "--request-id", "nope"]) == 2


# -- the gate-off serving path is byte-identical -----------------------------

class TestDefaultPathPinned:
    def test_push_off_serving_path_matches_the_poll_only_pin(
            self, monkeypatch):
        for var in ("SDTPU_TSDB", "SDTPU_ALERTS", "SDTPU_FEDERATION",
                    "SDTPU_NOTIFY_URL", "SDTPU_NOTIFY_ROUTES",
                    "SDTPU_TSDB_DIR", "SDTPU_PUSH",
                    "SDTPU_PUSH_CURSOR_BUF", "SDTPU_PUSH_WAIT_S",
                    "SDTPU_JOURNAL"):
            monkeypatch.delenv(var, raising=False)
        obs_tsdb.reset()
        obs_alerts.reset()
        obs_fed.reset()
        obs_notify.reset()
        obs_push.reset()
        obs_fleetlog.reset()
        engine = Engine(TINY, init_params(TINY), chunk_size=4,
                        state=GenerationState())
        disp = ServingDispatcher(
            engine, bucketer=ShapeBucketer(shapes=[(32, 32)], batches=[1]),
            window=0.0)
        r = disp.submit(GenerationPayload(
            prompt="a golden scenario cow", width=32, height=32,
            steps=4, seed=4321, sampler_name="Euler a"))
        # the SAME golden as the poll-only build: push off is not just
        # deterministic, it is byte-identical to pre-push serving
        _check("serving/federation-off-default", r)
        doc = obs_push.summary()
        assert doc["workers"] == {}
        assert doc["ticks"] == 0
        assert doc["buffer"] == {"retained": 0, "next_cursor": 0,
                                 "evicted_total": 0}
        timeline = obs_fleetlog.timeline()
        assert timeline["enabled"] is False
        assert timeline["count"] == 0


# -- acceptance e2e: chaos kill debuggable from one timeline GET --------------

class TestChaosKillTimeline:
    def test_kill_story_in_a_single_timeline_response(self, monkeypatch):
        from stable_diffusion_webui_distributed_tpu.obs import (
            prometheus as obs_prom,
        )
        from stable_diffusion_webui_distributed_tpu.runtime.config import (
            ConfigModel,
        )
        from stable_diffusion_webui_distributed_tpu.scheduler.worker \
            import StubBackend, StubBehavior, WorkerNode
        from stable_diffusion_webui_distributed_tpu.scheduler.world \
            import World
        from stable_diffusion_webui_distributed_tpu.server.api import (
            ApiServer,
        )
        from stable_diffusion_webui_distributed_tpu.sim import (
            chaos as sim_chaos,
        )

        for key, value in (("SDTPU_SIM", "1"), ("SDTPU_JOURNAL", "1"),
                           ("SDTPU_TSDB", "1"), ("SDTPU_ALERTS", "1"),
                           ("SDTPU_PUSH", "1"), ("SDTPU_PUSH_WAIT_S", "0"),
                           ("SDTPU_TSDB_INTERVAL_S", "0.05"),
                           ("SDTPU_ALERT_TIMESCALE", "0.01"),
                           ("SDTPU_OBS_HTTP_TIMEOUT_S", "2.0")):
            monkeypatch.setenv(key, value)
        monkeypatch.delenv("SDTPU_FEDERATION", raising=False)
        obs_prom.clear_histograms()
        obs_tsdb.reset()
        obs_alerts.reset()
        obs_fed.reset()
        obs_notify.reset()
        obs_push.reset()
        obs_fleetlog.reset()
        obs_journal.JOURNAL.clear()

        w = World(ConfigModel())
        nodes = {
            "alpha": WorkerNode("alpha", StubBackend(
                StubBehavior(seconds_per_image=0.001)), avg_ipm=2400.0),
            "victim": WorkerNode("victim", StubBackend(
                StubBehavior(seconds_per_image=0.001)), avg_ipm=2400.0),
        }
        servers = {}
        obs_push.set_source(w)

        def cycle(n, sleep_s=0.05):
            for _ in range(n):
                obs_push.tick()
                obs_tsdb.tick()
                time.sleep(sleep_s)

        try:
            for label, node in nodes.items():
                w.add_worker(node)
                srv = ApiServer(w, state=GenerationState(),
                                host="127.0.0.1", port=0).start()
                node.backend.address = "127.0.0.1"
                node.backend.port = srv.port
                servers[label] = srv

            # steady state: one fan-out request, then a few push cycles
            # so both workers' delta streams have flowed
            w.execute(GenerationPayload(
                prompt="push e2e steady", steps=8, width=512, height=512,
                batch_size=4, seed=99, request_id="push-e2e-000"))
            cycle(4)
            doc = obs_push.summary()
            assert set(doc["workers"]) == {"alpha", "victim"}
            assert all(s["mode"] == "push"
                       for s in doc["workers"].values())

            # the chaos: kill the victim mid-request; the scheduler
            # requeues its share onto the healthy worker
            plan = sim_chaos.ChaosPlan(
                [sim_chaos.Fault(kind="kill", worker="victim",
                                 at_request=1)], seed=0)
            sim_chaos.arm(plan)
            try:
                w.execute(GenerationPayload(
                    prompt="push e2e kill", steps=8, width=512,
                    height=512, batch_size=4, seed=99,
                    request_id="push-kill-001"))
            finally:
                sim_chaos.disarm()

            # the worker process dies outright: its API goes away and
            # the subscriber's fetches start failing
            servers.pop("victim").stop()
            time.sleep(max(0.3, obs_fed.stale_after_s() + 0.1))
            cycle(8)

            # --- the acceptance gate: ONE timeline GET tells the story
            timeline = _get_json(servers["alpha"].port,
                                 "/internal/fleet/timeline")
            events = timeline["events"]
            # the victim's lane holds its last streamed events
            assert any(e["node"] == "victim" for e in events)
            # the injected fault is on the axis
            assert any(e["event"] == "fault_injected" for e in events)
            # the stale alert fired, with its severity attached
            firings = [e for e in events if e["event"] == "alert_firing"
                       and e["attrs"].get("rule")
                       == "worker_metrics_stale"]
            assert firings, "worker_metrics_stale never fired"
            assert all(e["attrs"]["severity"] == "page" for e in firings)
            # the requeue left the victim and landed on the healthy node
            requeues = [e for e in events if e["event"] == "requeued"]
            assert any(e["attrs"].get("from_worker") == "victim"
                       and e["attrs"].get("to") == ["alpha"]
                       for e in requeues)
            # and the merge is causally clean
            assert timeline["violations"] == 0

            # the filtered view reassembles the killed request's story
            filtered = _get_json(
                servers["alpha"].port,
                "/internal/fleet/timeline?request_id=push-kill-001")
            names = {e["event"] for e in filtered["events"]}
            assert "job_failed" in names
            assert "requeued" in names
            assert "completed" in names

            # stream accounting: nothing lost, the victim is marked
            # stale, the healthy worker kept streaming
            doc = obs_push.summary()
            assert doc["event_loss"] == 0
            assert doc["workers"]["victim"]["stale"] is True
            assert doc["workers"]["victim"]["failures"] > 0
            assert doc["workers"]["alpha"]["stale"] is False
            assert doc["workers"]["alpha"]["last_error"] is None
        finally:
            for srv in servers.values():
                srv.stop()
            obs_notify.flush(5.0)
            obs_push.reset()
            obs_fleetlog.reset()
            obs_tsdb.reset()
            obs_alerts.reset()
            obs_fed.reset()
            obs_notify.reset()
            obs_journal.JOURNAL.clear()
            obs_prom.clear_histograms()

"""Serving layer: shape bucketer, dispatch metrics, continuous batching.

The acceptance scenario from the serving design: 8 concurrent requests
across 4 raw shapes must land on 2 bucket executables (<= 2 chunk
compiles), merge into coalesced device batches, and return seeds /
infotext / image bytes identical to serial execution of the same
payloads.  All assertions are host-side counts — no wall-clock.
"""

import threading
import time

import numpy as np
import pytest

from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload, b64png_to_array,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.scheduler.eta import (
    EtaCalibration, predict_eta,
)
from stable_diffusion_webui_distributed_tpu.serving.bucketer import (
    DEFAULT_BATCH_LADDER, DEFAULT_SHAPE_LADDER, ShapeBucketer,
)
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (
    ServingDispatcher,
)
from stable_diffusion_webui_distributed_tpu.serving.metrics import (
    METRICS, DispatchMetrics,
)
from test_pipeline import init_params


def payload(**kw):
    defaults = dict(prompt="a cow", steps=4, width=32, height=32,
                    seed=7, sampler_name="Euler a")
    defaults.update(kw)
    return GenerationPayload(**defaults)


class TestBucketer:
    def test_smallest_fitting_bucket(self):
        b = ShapeBucketer(shapes=DEFAULT_SHAPE_LADDER,
                          batches=DEFAULT_BATCH_LADDER)
        assert b.bucket_shape(500, 500) == (512, 512)
        assert b.bucket_shape(512, 512) == (512, 512)
        assert b.bucket_shape(513, 512) == (640, 640)
        assert b.bucket_shape(1025, 64) is None  # nothing fits -> raw

    def test_batch_ladder(self):
        b = ShapeBucketer(shapes=[(64, 64)], batches=[1, 2, 4, 8])
        assert b.bucket_batch(1) == 1
        assert b.bucket_batch(3) == 4
        assert b.bucket_batch(8) == 8
        assert b.bucket_batch(9) == 9  # ladder tops out: run raw

    def test_padding_ratio(self):
        b = ShapeBucketer(shapes=[(512, 512)], batches=[1])
        assert b.padding_ratio(512, 512) == pytest.approx(1.0)
        assert b.padding_ratio(256, 256) == pytest.approx(4.0)
        assert b.padding_ratio(4096, 4096) == pytest.approx(1.0)  # no fit

    def test_payload_pad_and_crop_round_trip(self):
        b = ShapeBucketer(shapes=[(32, 32)], batches=[4])
        p = payload(width=24, height=20)
        run, bucketed = b.bucket_payload(p)
        assert bucketed and (run.width, run.height) == (32, 32)
        assert run.group_size == 4
        assert (p.width, p.height) == (24, 20)  # original untouched
        img = np.arange(32 * 32 * 3, dtype=np.uint8).reshape(32, 32, 3)
        back = ShapeBucketer.crop(img, p.width, p.height)
        assert back.shape == (20, 24, 3)
        # center crop: offsets (32-20)//2 = 6 rows, (32-24)//2 = 4 cols
        np.testing.assert_array_equal(back, img[6:26, 4:28])
        assert ShapeBucketer.crop(img, 32, 32) is img  # exact hit: no-op

    def test_exact_hit_not_bucketed(self):
        b = ShapeBucketer(shapes=[(32, 32)], batches=[1])
        run, bucketed = b.bucket_payload(payload(width=32, height=32))
        assert not bucketed and (run.width, run.height) == (32, 32)

    def test_env_ladder_parse(self, monkeypatch):
        monkeypatch.setenv("SDTPU_BUCKET_LADDER", "64x64, 128x96")
        monkeypatch.setenv("SDTPU_BATCH_LADDER", "2, 4")
        b = ShapeBucketer()
        assert b.shapes == [(64, 64), (128, 96)]
        assert b.batches == [2, 4]

    def test_env_ladder_warn_and_default(self, monkeypatch):
        monkeypatch.setenv("SDTPU_BUCKET_LADDER", "not-a-ladder")
        monkeypatch.setenv("SDTPU_BATCH_LADDER", "4,-1")
        with pytest.warns(UserWarning, match="SDTPU_BUCKET_LADDER"):
            b = ShapeBucketer()
        assert set(b.shapes) == set(DEFAULT_SHAPE_LADDER)
        assert set(b.batches) == set(DEFAULT_BATCH_LADDER)

    def test_from_config(self, monkeypatch):
        monkeypatch.delenv("SDTPU_BUCKET_LADDER", raising=False)
        monkeypatch.delenv("SDTPU_BATCH_LADDER", raising=False)

        class Cfg:
            bucket_ladder = "96x96"
            batch_ladder = "1,2"

        b = ShapeBucketer.from_config(Cfg())
        assert b.shapes == [(96, 96)] and b.batches == [1, 2]
        # env wins over config fields
        monkeypatch.setenv("SDTPU_BUCKET_LADDER", "48x48")
        assert ShapeBucketer.from_config(Cfg()).shapes == [(48, 48)]


class TestMetrics:
    def test_counters_and_summary(self):
        m = DispatchMetrics()
        m.record_compile("chunk")
        m.record_compile("chunk")
        m.record_cache_hit("chunk")
        m.record_request(bucketed=True, padding_ratio=2.0)
        m.record_request(bucketed=False, padding_ratio=1.0)
        m.record_request(bucketed=False, bypassed=True)
        m.record_dispatch(4)
        m.record_dispatch(1)
        m.record_queue_wait(0.2)
        m.record_queue_wait(0.4)
        s = m.summary()
        assert m.compile_count("chunk") == 2
        assert s["cache_hits"] == {"chunk": 1}
        assert s["requests"] == 3 and s["bucket_bypasses"] == 1
        assert s["bucket_hit_rate"] == pytest.approx(0.5)
        assert s["dispatches"] == 2 and s["coalesced_dispatches"] == 1
        assert m.coalesce_factor() == pytest.approx(2.5)
        assert m.avg_queue_wait() == pytest.approx(0.3)
        assert m.avg_padding_ratio() == pytest.approx(1.5)
        m.clear()
        assert m.summary()["requests"] == 0
        assert m.coalesce_factor() == 0.0


class TestEtaOverheads:
    def test_padding_scales_and_wait_adds(self):
        cal = EtaCalibration(avg_ipm=6.0)
        p = payload(batch_size=2, steps=20, width=512, height=512)
        base = predict_eta(cal, p)  # 20 s at the benchmark point
        assert predict_eta(cal, p, padding_overhead=2.0) == \
            pytest.approx(2.0 * base)
        assert predict_eta(cal, p, queue_wait=5.0) == \
            pytest.approx(base + 5.0)
        # wait is latency, not compute: a sub-1 padding factor never
        # shrinks the estimate and negative wait never subtracts
        assert predict_eta(cal, p, padding_overhead=0.5,
                           queue_wait=-3.0) == pytest.approx(base)

    def test_dispatcher_eta_overhead(self):
        METRICS.clear()
        disp = ServingDispatcher(
            None, bucketer=ShapeBucketer(shapes=[(64, 64)], batches=[1]),
            window=0.2)
        over = disp.eta_overhead(payload(width=32, height=32))
        assert over["padding_overhead"] == pytest.approx(4.0)
        # no observed waits yet: floor at half the coalesce window
        assert over["queue_wait"] == pytest.approx(0.1)


@pytest.fixture(scope="module")
def engine():
    return Engine(TINY, init_params(TINY), chunk_size=4,
                  state=GenerationState())


@pytest.fixture(scope="module")
def bucketer():
    # batches=[4]: every group partition pads to the same compiled batch,
    # so the compile count is deterministic under thread scheduling
    return ShapeBucketer(shapes=[(32, 32), (48, 48)], batches=[4])


class TestContinuousBatching:
    # 8 requests over 4 raw shapes that map onto 2 buckets; prompts vary
    # per shape so merged conditioning really is per-request
    SHAPES = [(32, 32), (24, 32), (48, 48), (40, 40)]

    def _payloads(self):
        out = []
        for i, (w, h) in enumerate(self.SHAPES):
            for k in range(2):
                out.append(payload(width=w, height=h, seed=100 + i * 10 + k,
                                   prompt=f"cow {i}"))
        return out

    def test_acceptance_coalesce_and_byte_exactness(self, engine, bucketer):
        serial = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        coalesced = ServingDispatcher(engine, bucketer=bucketer, window=0.6)

        METRICS.clear()
        baseline = [serial.submit(p) for p in self._payloads()]
        assert METRICS.compile_count("chunk") <= 2  # one per shape bucket
        assert METRICS.summary()["dispatches"] == 8

        METRICS.clear()
        results = [None] * 8
        errors = []

        def run(i, p):
            try:
                results[i] = coalesced.submit(p)
            except Exception as e:  # noqa: BLE001 — surfaced by assert
                errors.append(e)

        threads = [threading.Thread(target=run, args=(i, p))
                   for i, p in enumerate(self._payloads())]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

        s = METRICS.summary()
        # the whole point: 4 raw shapes -> 2 executables, and the serial
        # phase already built both, so the concurrent phase compiles NOTHING
        assert s["compiles"].get("chunk", 0) == 0
        assert s["coalesced_dispatches"] >= 1
        assert s["coalesce_factor"] >= 2.0
        assert s["requests"] == 8 and s["bucket_bypasses"] == 0

        for got, want in zip(results, baseline):
            assert got.seeds == want.seeds
            assert got.infotexts == want.infotexts
            assert got.images == want.images  # pixel bytes, not just shape

    def test_infotext_reports_requested_size(self, engine, bucketer):
        disp = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        r = disp.submit(payload(width=24, height=32, seed=5))
        assert len(r.images) == 1
        assert b64png_to_array(r.images[0]).shape == (32, 24, 3)
        assert "Size: 24x32" in r.infotexts[0]
        assert r.seeds == [5]

    def test_cancel_drops_only_one_requester(self, engine, bucketer):
        disp = ServingDispatcher(engine, bucketer=bucketer, window=0.6)
        solo = ServingDispatcher(engine, bucketer=bucketer, window=0.0)
        keep = payload(width=32, height=32, seed=11,
                       request_id="req-keep")
        drop = payload(width=32, height=32, seed=12,
                       request_id="req-drop")
        results = {}

        def run(name, p):
            results[name] = disp.submit(p)

        threads = [threading.Thread(target=run, args=("keep", keep)),
                   threading.Thread(target=run, args=("drop", drop))]
        for t in threads:
            t.start()
        time.sleep(0.15)  # inside the coalesce window
        assert disp.cancel("req-drop")
        assert not disp.cancel("no-such-request")
        for t in threads:
            t.join()

        cancelled = results["drop"]
        assert cancelled.images == []
        assert cancelled.parameters.get("cancelled") is True
        # the co-batched survivor is byte-identical to running alone
        alone = solo.submit(payload(width=32, height=32, seed=11))
        assert results["keep"].seeds == alone.seeds
        assert results["keep"].images == alone.images
        assert results["keep"].infotexts == alone.infotexts

    def test_solo_bucketed_run_restored(self, engine):
        # batch above the ladder top -> not coalescable -> solo path,
        # still shape-bucketed and cropped + infotext-rebuilt afterwards
        disp = ServingDispatcher(
            engine, bucketer=ShapeBucketer(shapes=[(32, 32)], batches=[1]),
            window=0.0)
        r = disp.submit(payload(width=24, height=32, seed=21, batch_size=2))
        assert len(r.images) == 2
        for b64 in r.images:
            assert b64png_to_array(b64).shape == (32, 24, 3)
        assert all("Size: 24x32" in t for t in r.infotexts)
        assert r.seeds == [21, 22]

    def test_warmup_prebuilds_ladder(self, engine):
        from stable_diffusion_webui_distributed_tpu.serving.warmup import (
            warmup_engine,
        )

        b = ShapeBucketer(shapes=[(32, 32)], batches=[1])
        report = warmup_engine(engine, b, steps=4, sampler="Euler a")
        assert report["skipped"] is False
        assert report["buckets"] == [(32, 32, 1)]
        assert report["steps"] == 4 and report["sampler"] == "Euler a"
        assert isinstance(report["stage_builds"], dict)
        # a second sweep over the same ladder builds nothing new
        again = warmup_engine(engine, b, steps=4, sampler="Euler a")
        assert again["stage_builds"] == {}

    def test_warmup_env_disable(self, monkeypatch):
        from stable_diffusion_webui_distributed_tpu.serving.warmup import (
            warmup_engine,
        )

        monkeypatch.setenv("SDTPU_WARMUP", "0")
        report = warmup_engine(None)  # engine untouched when disabled
        assert report["skipped"] is True


class TestPrecisionDispatch:
    """Per-request serving precision (pipeline/precision.py) as a dispatch
    group-key axis: mixed bf16/int8 traffic on ONE shape bucket must hold
    the compile budget (one chunk executable per precision actually used —
    never coalesce across precisions, never an unbounded key)."""

    # the (48, 48) bucket at batch 2 is disjoint from every other class's
    # chunk keys on the shared module engine, so compile counts are exact
    # (steps stay at 4: one chunk-scan length, one executable per precision)
    def _bucketer(self):
        return ShapeBucketer(shapes=[(48, 48)], batches=[2])

    def test_mixed_precision_compile_budget(self, engine):
        disp = ServingDispatcher(engine, bucketer=self._bucketer(),
                                 window=0.0)

        METRICS.clear()
        bf16 = [disp.submit(payload(seed=31)),
                disp.submit(payload(seed=32))]
        # one bucket, one precision -> exactly one chunk executable
        assert METRICS.compile_count("chunk") == 1

        int8 = [disp.submit(payload(
                    seed=31, override_settings={"precision": "int8"})),
                disp.submit(payload(seed=32, precision="int8"))]
        s = METRICS.summary()
        # the int8 rung adds exactly ONE more executable for the same
        # bucket (<= 3 precisions x <= 2 step-cache variants per bucket),
        # shared by both the override_settings and the field spelling
        assert s["compiles"].get("chunk", 0) == 2
        assert s["precision"]["bf16"]["requests"] == 2
        assert s["precision"]["int8"]["requests"] == 2

        # engagement: the quantized executable really ran (same seeds,
        # different pixels); the two int8 spellings agree byte-for-byte
        assert int8[0].images != bf16[0].images
        assert int8[0].seeds == bf16[0].seeds
        assert int8[1].images != bf16[1].images

    def test_unknown_precision_buckets_to_default(self, engine):
        # off-ladder names never mint a fourth executable: they resolve to
        # the policy default and ride the existing bf16 group
        disp = ServingDispatcher(engine, bucketer=self._bucketer(),
                                 window=0.0)
        base = disp.submit(payload(seed=33))
        METRICS.clear()
        odd = disp.submit(payload(
            seed=33, override_settings={"precision": "fp4-turbo"}))
        assert METRICS.compile_count("chunk") == 0
        assert odd.images == base.images

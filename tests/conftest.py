"""Test harness: force an 8-device virtual CPU platform before JAX initializes.

Multi-chip behavior (shard_map/pjit over a Mesh) is tested without TPU
hardware per the standard JAX recipe: 8 virtual CPU devices via XLA_FLAGS.
"""

import os

# Must be set before jax (or anything importing jax) is imported.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    import jax

    return jax.devices()


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("dp", "tp"))

"""Test harness: force an 8-device virtual CPU platform before JAX initializes.

Multi-chip behavior (shard_map/pjit over a Mesh) is tested without TPU
hardware per the standard JAX recipe: 8 virtual CPU devices via XLA_FLAGS.
"""

import os
import sys

# Force the virtual CPU platform (must happen before jax import).
os.environ["JAX_PLATFORMS"] = "cpu"


def pytest_configure(config):
    """Keep test runs off the real TPU chip.

    The harness environment routes EVERY python process through the one real
    TPU chip: a sitecustomize hook (PYTHONPATH=/root/.axon_site) claims the
    chip at interpreter startup whenever PALLAS_AXON_POOL_IPS is set.
    Concurrent pythons then serialize (or deadlock) on the device claim — a
    pytest run would both hold the chip hostage and hang if anything else
    holds it. Tests belong on the virtual CPU platform; only bench.py uses
    the real TPU.

    The claim happens before any pytest code, so once we detect it we
    re-exec with a scrubbed environment. Global capture must be stopped
    first: it has already redirected fd 1/2 to tempfiles, and an exec'd
    process inheriting those would lose every byte of output.
    """
    if os.environ.get("SDTPU_LOCKSAN") == "1":
        # Patch the threading lock factories BEFORE test modules import
        # the package, so every Class.attr lock is wrapped and named.
        from stable_diffusion_webui_distributed_tpu.runtime import locksan

        locksan.install()
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        try:
            capman.stop_global_capturing()
        except Exception:
            pass
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest", *sys.argv[1:]], env)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

#: modules whose tests compile real (tiny) model pipelines — minutes of XLA
#: CPU compile time each. Everything else forms the `-m fast` tier (~2 min:
#: scheduler, config/runtime, server, samplers, xyz, cli, native, prompt).
_SLOW_MODULES = {
    "test_pipeline", "test_adapters", "test_inpaint_model",
    "test_embeddings", "test_registry", "test_esrgan", "test_goldens",
}


def pytest_sessionfinish(session, exitstatus):
    """SDTPU_LOCKSAN=1: diff the observed lock-order graph against the
    static LK005 graph; an edge the static model has no path for fails
    the run — the model must not silently diverge from reality.

    SDTPU_LOCKSAN_ORDER (default on with the sanitizer) layers the
    ordering checks on top: a Goodlock-style cycle in the union of the
    observed per-thread acquisition edges, a ``Condition.wait`` entered
    while holding an unrelated lock, or a ``lockorder a<b`` annotation
    no test exercised each fail the session — a cycle that happened not
    to interleave fatally this run is still a deadlock waiting for the
    right schedule, and an unexercised annotation is suppressing the
    static analyzer on faith."""
    if os.environ.get("SDTPU_LOCKSAN") != "1":
        return
    from stable_diffusion_webui_distributed_tpu.runtime import locksan

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    failures = []
    diverged = locksan.divergence(locksan.observed_edges(),
                                  locksan.static_graph(root))
    if diverged:
        failures.append(
            "observed lock orderings missing from the static graph "
            "(analysis/locks.py):\n" + "\n".join(
                f"  {a} -> {b}" for a, b in diverged))
    if os.environ.get("SDTPU_LOCKSAN_ORDER", "1") != "0":
        cycles = locksan.runtime_cycles()
        if cycles:
            failures.append(
                "runtime lock-order cycles (Goodlock union of per-thread "
                "acquisition edges):\n" + "\n".join(
                    "  " + " -> ".join(c) for c in cycles))
        waits = locksan.wait_violations()
        if waits:
            failures.append(
                "Condition.wait entered while holding unrelated lock(s):\n"
                + "\n".join(f"  held {list(held)} waiting on {cv} "
                            f"in thread {thread}"
                            for held, cv, thread in waits))
        unexercised = locksan.declared_orders(root) \
            - locksan.observed_edges()
        if unexercised:
            failures.append(
                "lockorder annotations no test exercised (an order the "
                "suite cannot demonstrate may not suppress LK005):\n"
                + "\n".join(f"  {a} < {b}"
                            for a, b in sorted(unexercised)))
    if failures:
        print("\nlocksan session gate failed:", file=sys.stderr)
        for f in failures:
            print(f, file=sys.stderr)
        session.exitstatus = 1
    else:
        print(f"\nlocksan: {len(locksan.observed_edges())} observed "
              f"edge(s), zero divergence, zero runtime cycles, zero "
              f"wait-while-holding violations", file=sys.stderr)


def pytest_collection_modifyitems(config, items):
    """Two-tier suite: `pytest -m fast` for the iteration loop, `-m slow`
    for the compiled-pipeline tests (see README "Running the tests")."""
    for item in items:
        module = item.nodeid.split("/")[-1].split(".py")[0]
        slow = module in _SLOW_MODULES \
            or item.get_closest_marker("slow") is not None
        item.add_marker(pytest.mark.slow if slow else pytest.mark.fast)


@pytest.fixture(scope="session")
def devices():
    import jax

    return jax.devices()


@pytest.fixture(scope="session")
def mesh8():
    """dp=4 x tp=2 mesh over the virtual devices, built through the
    production mesh constructor (runtime/mesh.py)."""
    from stable_diffusion_webui_distributed_tpu.runtime.mesh import build_mesh

    return build_mesh("dp=4,tp=2")

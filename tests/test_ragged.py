"""Ragged dispatch: pallas ragged attention + true-length batching.

Kernel half: the interpret-mode pallas kernel (ops/ragged_attention.py)
against its dense masked reference across head dims, block shapes and
non-divisor true lengths — plus the contract that the CPU default path IS
the reference (bit-exact, so tier-1 goldens cannot drift).

Serving half: under SDTPU_RAGGED, mixed-height traffic on one coarse
bucket coalesces into ONE group and ONE chunk executable while every
request stays byte-identical to running alone; with the knob unset the
default path is hash-pinned via the goldens mechanism.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.ops.ragged_attention import (
    ragged_attention, ragged_attention_reference,
)
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload, b64png_to_array,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.serving.bucketer import (
    ShapeBucketer,
)
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (
    ServingDispatcher,
)
from stable_diffusion_webui_distributed_tpu.serving.metrics import METRICS
from test_goldens import _check
from test_pipeline import init_params

RNG = np.random.default_rng(7)


def qkv(b, t, h, d, s=None):
    s = t if s is None else s
    q = jnp.asarray(RNG.standard_normal((b, t, h, d), np.float32))
    k = jnp.asarray(RNG.standard_normal((b, s, h, d), np.float32))
    v = jnp.asarray(RNG.standard_normal((b, s, h, d), np.float32))
    return q, k, v


def tl(*lens):
    return jnp.asarray(lens, jnp.int32)


def payload(**kw):
    defaults = dict(prompt="a cow", steps=4, width=32, height=32,
                    seed=7, sampler_name="Euler a")
    defaults.update(kw)
    return GenerationPayload(**defaults)


class TestRaggedKernel:
    # head dim 40 (SD15's 8-head 320-ch blocks) alongside the tiling-
    # friendly powers of two
    @pytest.mark.parametrize("d", [16, 32, 40, 64])
    def test_matches_reference_across_head_dims(self, d):
        q, k, v = qkv(3, 256, 2, d)
        lens = tl(256, 130, 77)
        out = ragged_attention(q, k, v, lens, block_q=128, block_k=128,
                               interpret=True)
        ref = ragged_attention_reference(q, k, v, lens, q_true_len=lens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    # lengths that straddle tile boundaries every way a prefix can:
    # exactly one tile, one past, one short, and a single valid token
    @pytest.mark.parametrize("lens", [(256, 77, 130, 1),
                                      (129, 128, 127, 255)])
    def test_non_divisor_true_lengths(self, lens):
        q, k, v = qkv(len(lens), 256, 2, 32)
        out = ragged_attention(q, k, v, tl(*lens), block_q=128,
                               block_k=128, interpret=True)
        ref = ragged_attention_reference(q, k, v, tl(*lens),
                                         q_true_len=tl(*lens))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_full_length_equals_dense(self):
        # true_len == bucket: ragged must reduce to plain attention
        q, k, v = qkv(2, 128, 4, 32)
        out = ragged_attention(q, k, v, tl(128, 128), block_q=64,
                               block_k=64, interpret=True)
        dense = jax.nn.dot_product_attention(q, k, v, scale=1 / 32 ** 0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   rtol=2e-5, atol=2e-5)

    def test_mixed_rows_match_per_row_dense(self):
        # each row's valid prefix equals dense attention over JUST that
        # prefix, and the padded tail comes out exactly zero
        q, k, v = qkv(4, 256, 2, 32)
        lens = (256, 192, 100, 33)
        out = np.asarray(ragged_attention(q, k, v, tl(*lens), block_q=64,
                                          block_k=64, interpret=True))
        for b, n in enumerate(lens):
            dense = jax.nn.dot_product_attention(
                q[b:b + 1, :n], k[b:b + 1, :n], v[b:b + 1, :n],
                scale=1 / 32 ** 0.5)
            np.testing.assert_allclose(out[b, :n], np.asarray(dense[0]),
                                       rtol=2e-5, atol=2e-5)
            assert np.all(out[b, n:] == 0.0)

    def test_padded_kv_tail_is_inert(self):
        # garbage in the padded k/v tail must not perturb valid outputs:
        # masked probabilities are exactly 0.0, so the fold is bitwise
        # identical
        q, k, v = qkv(2, 128, 2, 16)
        lens = tl(100, 64)
        base = ragged_attention(q, k, v, lens, block_q=64, block_k=64,
                                interpret=True)
        k2 = k.at[0, 100:].set(1e4).at[1, 64:].set(-1e4)
        v2 = v.at[0, 100:].set(-1e4).at[1, 64:].set(1e4)
        pert = ragged_attention(q, k2, v2, lens, block_q=64, block_k=64,
                                interpret=True)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(pert))

    def test_non_tiling_falls_back_to_reference(self):
        # t=100 doesn't tile at block 64 -> the dense reference runs, so
        # equality is exact, not approximate
        q, k, v = qkv(2, 100, 2, 16)
        lens = tl(100, 40)
        out = ragged_attention(q, k, v, lens, block_q=64, block_k=64,
                               interpret=True)
        ref = ragged_attention_reference(q, k, v, lens, q_true_len=lens)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_cpu_default_path_is_reference(self):
        # off-TPU with interpret unspecified the execution path IS the
        # oracle — the bit-exactness tier-1 goldens rely on
        q, k, v = qkv(1, 128, 2, 16)
        lens = tl(57)
        out = ragged_attention(q, k, v, lens)
        ref = ragged_attention_reference(q, k, v, lens, q_true_len=lens)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_jittable_with_traced_lengths(self):
        # true_len must be usable as traced data (RC001: lengths are NOT
        # compile-key statics) — one trace serves different length vectors
        q, k, v = qkv(2, 128, 2, 16)
        traces = []

        @jax.jit
        def f(a, b, c, n):
            traces.append(None)
            return ragged_attention(a, b, c, n, block_q=64, block_k=64,
                                    interpret=True)

        for lens in (tl(128, 7), tl(33, 90)):
            ref = ragged_attention_reference(q, k, v, lens,
                                             q_true_len=lens)
            np.testing.assert_allclose(np.asarray(f(q, k, v, lens)),
                                       np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)
        assert len(traces) == 1  # second length vector reused the trace


class TestRaggedBucketer:
    def test_bucket_shape_ragged_tallest_in_width_class(self):
        b = ShapeBucketer(shapes=[(64, 16), (64, 64), (96, 48)],
                          batches=[1])
        # width class 64 tops out at height 64 — every shorter request
        # shares that executable
        assert b.bucket_shape_ragged(64, 20) == (64, 64)
        assert b.bucket_shape_ragged(48, 64) == (64, 64)
        assert b.bucket_shape_ragged(80, 40) == (96, 48)
        assert b.bucket_shape_ragged(80, 64) is None  # no class holds it

    def test_ragged_ladder_env_override(self, monkeypatch):
        monkeypatch.setenv("SDTPU_RAGGED_LADDER", "64x64")
        b = ShapeBucketer(shapes=[(32, 32), (48, 48)], batches=[1])
        assert b.bucket_shape_ragged(40, 40) == (64, 64)
        assert b.bucket_shape(40, 40) == (48, 48)  # classic path untouched

    def test_padding_ratio_modes(self, monkeypatch):
        b = ShapeBucketer(shapes=[(64, 64)], batches=[4])
        monkeypatch.delenv("SDTPU_RAGGED", raising=False)
        # classic: full area ratio; batch padding multiplies in when given
        assert b.padding_ratio(32, 16) == pytest.approx(8.0)
        assert b.padding_ratio(32, 16, batch=1) == pytest.approx(32.0)
        assert b.padding_ratio(64, 64, batch=3) == pytest.approx(4 / 3)
        # ragged: only the width snap is computed — tail rows are masked
        monkeypatch.setenv("SDTPU_RAGGED", "1")
        assert b.padding_ratio(32, 16) == pytest.approx(2.0)
        assert b.padding_ratio(64, 16) == pytest.approx(1.0)

    def test_marker_stamped_with_true_dims(self, monkeypatch):
        monkeypatch.setenv("SDTPU_RAGGED", "1")
        b = ShapeBucketer(shapes=[(64, 64)], batches=[1])
        run, bucketed = b.bucket_payload(payload(width=48, height=32),
                                         ragged=True)
        assert bucketed and (run.width, run.height) == (64, 64)
        assert run.override_settings["ragged_true_wh"] == [48, 32]
        # exact hit: still marked (shares the ragged executable), but the
        # classic entry point never mints the marker
        exact, _ = b.bucket_payload(payload(width=64, height=64),
                                    ragged=True)
        assert exact.override_settings["ragged_true_wh"] == [64, 64]
        classic, _ = b.bucket_payload(payload(width=48, height=32))
        assert "ragged_true_wh" not in (classic.override_settings or {})

    def test_crop_ragged_top_aligned(self):
        img = np.arange(64 * 64 * 3, dtype=np.int64).astype(
            np.uint8).reshape(64, 64, 3)
        back = ShapeBucketer.crop_ragged(img, 48, 32)
        assert back.shape == (32, 48, 3)
        # rows top-aligned (valid prefix), columns center-cropped
        np.testing.assert_array_equal(back, img[:32, 8:56])
        assert ShapeBucketer.crop_ragged(img, 64, 64) is img


@pytest.fixture(scope="module")
def engine():
    return Engine(TINY, init_params(TINY), chunk_size=4,
                  state=GenerationState())


class TestRaggedDispatch:
    # three heights in ONE 64-wide class: the whole point is that they
    # share a single executable
    SHAPES = [(64, 64), (64, 48), (48, 32)]

    def _payloads(self):
        return [payload(width=w, height=h, seed=200 + i,
                        prompt=f"ragged cow {i}")
                for i, (w, h) in enumerate(self.SHAPES)]

    def test_mixed_heights_one_group_byte_exact(self, engine, monkeypatch):
        monkeypatch.setenv("SDTPU_RAGGED", "1")
        bucketer = ShapeBucketer(shapes=[(64, 64)], batches=[1, 2, 4])
        coalesced = ServingDispatcher(engine, bucketer=bucketer,
                                      window=0.6)
        solo = ServingDispatcher(engine, bucketer=bucketer, window=0.0)

        METRICS.clear()
        results = [None] * len(self.SHAPES)
        errors = []

        def run(i, p):
            try:
                results[i] = coalesced.submit(p)
            except Exception as e:  # noqa: BLE001 — surfaced by assert
                errors.append(e)

        threads = [threading.Thread(target=run, args=(i, p))
                   for i, p in enumerate(self._payloads())]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

        s = METRICS.summary()
        # three raw shapes -> ONE group, ONE ragged chunk executable
        assert s["dispatches"] == 1
        assert s["coalesced_dispatches"] == 1
        assert s["compiles"].get("chunk", 0) == 1

        # every image cropped back to its requested size
        for r, (w, h) in zip(results, self.SHAPES):
            assert b64png_to_array(r.images[0]).shape == (h, w, 3)
            assert f"Size: {w}x{h}" in r.infotexts[0]

        # byte-identical to running each request alone (solo adds only
        # the batch-1 variant of the same ragged executable)
        for got, p in zip(results, self._payloads()):
            want = solo.submit(p)
            assert got.seeds == want.seeds
            assert got.images == want.images  # pixel bytes, not shapes
        assert METRICS.summary()["compiles"].get("chunk", 0) == 2

    def test_stepcache_work_stays_classic(self, engine, monkeypatch):
        # deep-feature carry assumes dense rows: a step-cache request is
        # ragged-ineligible and must NOT carry the marker
        monkeypatch.setenv("SDTPU_RAGGED", "1")
        disp = ServingDispatcher(
            engine, bucketer=ShapeBucketer(shapes=[(64, 64)], batches=[1]),
            window=0.0)
        p = payload(width=64, height=48,
                    override_settings={"deepcache": 2})
        assert not disp._ragged_eligible(p)
        assert disp._ragged_eligible(payload(width=64, height=48))

    def test_default_off_path_hash_pinned(self, engine, monkeypatch):
        # SDTPU_RAGGED unset: the serving path must stay byte-identical
        # across refactors — frozen through the goldens mechanism
        monkeypatch.delenv("SDTPU_RAGGED", raising=False)
        disp = ServingDispatcher(
            engine, bucketer=ShapeBucketer(shapes=[(32, 32)], batches=[1]),
            window=0.0)
        r = disp.submit(payload(width=32, height=32, seed=1234,
                                prompt="a golden cow"))
        _check("serving/ragged-off-default", r)

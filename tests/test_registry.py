"""ModelRegistry tests: checkpoint discovery, activation from a real
single-file safetensors checkpoint, the orbax converted-params cache, and
family sidecar override."""

import json
import os

import numpy as np
import pytest

from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.pipeline.registry import (
    ModelRegistry,
)
from stable_diffusion_webui_distributed_tpu.runtime import dtypes
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)

from test_models import (
    make_ldm_clip_hf,
    make_ldm_unet,
    make_ldm_vae,
)


def write_tiny_checkpoint(model_dir, name="tinymodel"):
    from safetensors.numpy import save_file

    sd = {}
    sd.update(make_ldm_clip_hf(TINY.text_encoder))
    sd.update(make_ldm_unet(TINY.unet))
    sd.update(make_ldm_vae(TINY.vae))
    os.makedirs(model_dir, exist_ok=True)
    path = os.path.join(model_dir, f"{name}.safetensors")
    save_file(sd, path)
    with open(path + ".json", "w") as f:
        json.dump({"family": "tiny"}, f)
    return path


class TestRegistry:
    def test_discovery_and_activation(self, tmp_path):
        model_dir = str(tmp_path / "models")
        write_tiny_checkpoint(model_dir)
        reg = ModelRegistry(model_dir, policy=dtypes.F32,
                            state=GenerationState())
        assert "tinymodel" in reg.available()
        engine = reg.activate("tinymodel")
        assert reg.current_name == "tinymodel"
        r = engine.txt2img(GenerationPayload(
            prompt="cow", steps=3, width=32, height=32, seed=7))
        assert len(r.images) == 1

    def test_orbax_cache_roundtrip(self, tmp_path):
        model_dir = str(tmp_path / "models")
        write_tiny_checkpoint(model_dir)
        reg = ModelRegistry(model_dir, policy=dtypes.F32,
                            state=GenerationState())
        engine1 = reg.activate("tinymodel")
        img1 = engine1.txt2img(GenerationPayload(
            prompt="cow", steps=3, width=32, height=32, seed=7)).images[0]
        cache = tmp_path / "models" / ".sdtpu-cache" / "tinymodel"
        assert (cache / "meta.json").exists()

        # a fresh registry restores from the cache and reproduces exactly
        reg2 = ModelRegistry(model_dir, policy=dtypes.F32,
                             state=GenerationState())
        engine2 = reg2.activate("tinymodel")
        img2 = engine2.txt2img(GenerationPayload(
            prompt="cow", steps=3, width=32, height=32, seed=7)).images[0]
        assert img1 == img2

    def test_stale_cache_invalidated(self, tmp_path):
        model_dir = str(tmp_path / "models")
        path = write_tiny_checkpoint(model_dir)
        reg = ModelRegistry(model_dir, policy=dtypes.F32,
                            state=GenerationState())
        reg.activate("tinymodel")
        # touch the source: cache must be considered stale, not served
        os.utime(path, (os.path.getmtime(path) + 10,) * 2)
        reg2 = ModelRegistry(model_dir, policy=dtypes.F32,
                             state=GenerationState())
        assert reg2._load_param_cache("tinymodel", path) is None

    def test_unknown_model_raises(self, tmp_path):
        reg = ModelRegistry(str(tmp_path), policy=dtypes.F32)
        with pytest.raises(KeyError):
            reg.activate("nope")

    def test_vae_override_and_restore(self, tmp_path):
        from safetensors.numpy import save_file

        from test_models import make_ldm_vae

        model_dir = str(tmp_path / "models")
        write_tiny_checkpoint(model_dir)
        # standalone VAE with the bare key layout (no first_stage_model.)
        vae_sd = {k[len("first_stage_model."):]: v
                  for k, v in make_ldm_vae(TINY.vae).items()}
        os.makedirs(os.path.join(model_dir, "VAE"))
        save_file(vae_sd, os.path.join(model_dir, "VAE", "alt.safetensors"))

        reg = ModelRegistry(model_dir, policy=dtypes.F32,
                            state=GenerationState())
        engine = reg.activate("tinymodel")
        assert "alt" in reg.available_vaes()
        p = GenerationPayload(prompt="v", steps=2, width=32, height=32,
                              seed=3)
        base = engine.txt2img(p).images[0]
        assert reg.set_vae("alt")
        swapped = engine.txt2img(p).images[0]
        assert swapped != base
        assert reg.set_vae("Automatic")
        restored = engine.txt2img(p).images[0]
        assert restored == base
        assert not reg.set_vae("nonexistent")


class TestChunkKnob:
    def test_sdtpu_chunk_env_reaches_engines(self, monkeypatch, tmp_path):
        """README documents SDTPU_CHUNK as a deployment knob — the registry
        (server/CLI engine factory) must honor it, not just bench.py."""
        from stable_diffusion_webui_distributed_tpu.pipeline.registry import (
            ModelRegistry,
        )

        monkeypatch.setenv("SDTPU_CHUNK", "7")
        reg = ModelRegistry(str(tmp_path))
        assert reg.chunk_size == 7
        # explicit argument still wins
        reg2 = ModelRegistry(str(tmp_path), chunk_size=3)
        assert reg2.chunk_size == 3

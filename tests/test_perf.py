"""PR 8 observability: the perf ledger and its serving surfaces.

Covers the device-time-attribution layer end to end on CPU:

- ledger math (MFU against a forced peak, padding ratio/waste, bounded
  group rings, SLO attainment + burn rate) on fresh ``PerfLedger``s;
- the executable census against the contracted <=2 step-cache x <=3
  precision budget, driven by REAL mixed cadence+precision traffic
  through a ``ServingDispatcher`` on one shape bucket, plus a synthetic
  over-budget key set that must trip the alarm;
- the off-by-default discipline: with every ``SDTPU_PERF*`` knob unset
  the dispatch output is byte-identical to the instrumented-on run;
- Prometheus label hygiene for user-supplied tenant/class names
  (control characters, quotes, newlines, kilobyte strings);
- flight-recorder perf attribution and ring boundedness under churn;
- the ``/internal/status`` schema snapshot and the new ``/internal/perf``,
  ``/internal/executables``, ``/internal/autoscale`` and GET
  ``/internal/profile`` endpoints over real HTTP.
"""

import json
import os
import urllib.error
import urllib.request

import pytest

from stable_diffusion_webui_distributed_tpu.fleet import slices
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.obs import perf
from stable_diffusion_webui_distributed_tpu.obs import prometheus as obs_prom
from stable_diffusion_webui_distributed_tpu.obs.flightrec import (
    FlightRecorder,
)
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.serving.bucketer import (
    ShapeBucketer,
)
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (
    ServingDispatcher,
)
from test_pipeline import init_params


def payload(**kw):
    defaults = dict(prompt="a cow", steps=4, width=32, height=32,
                    seed=7, sampler_name="Euler a")
    defaults.update(kw)
    return GenerationPayload(**defaults)


@pytest.fixture(scope="module")
def engine():
    return Engine(TINY, init_params(TINY), chunk_size=4,
                  state=GenerationState())


def _dispatcher(engine):
    # one disjoint (48, 48) bucket at batch 2: compile keys stay exact
    # and never collide with other modules' buckets on a shared cache
    return ServingDispatcher(
        engine, bucketer=ShapeBucketer(shapes=[(48, 48)], batches=[2]),
        window=0.0)


@pytest.fixture()
def clean_ledger():
    perf.LEDGER.clear()
    yield perf.LEDGER
    perf.LEDGER.clear()


def _record_one(led, **kw):
    args = dict(bucket="64x64", cadence=1, precision="bf16",
                device_s=2.0, flops=1e12, requests=2, batch_raw=2,
                batch_run=4, true_pixels=3000, padded_pixels=4000)
    args.update(kw)
    led.record_dispatch(**args)


# -- ledger math -------------------------------------------------------------

class TestLedgerMath:
    def test_mfu_against_forced_peak(self, monkeypatch):
        # 1e12 FLOPs over 2 s against a forced 1e12 FLOP/s peak: MFU 0.5
        # exactly, deterministic on any host
        monkeypatch.setenv("SDTPU_PERF", "1")
        monkeypatch.setenv("SDTPU_PERF_PEAK_FLOPS", "1e12")
        led = perf.PerfLedger(max_groups=8)
        _record_one(led)
        (g,) = led.summary()["groups"]
        assert g["bucket"] == "64x64"
        assert g["mfu"] == pytest.approx(0.5)
        assert g["padding_ratio"] == pytest.approx(4000 / 3000)
        assert g["padding_waste"] == pytest.approx(0.25)
        assert g["dispatches"] == 1 and g["requests"] == 2

    def test_cpu_without_override_never_fabricates_mfu(self, monkeypatch):
        monkeypatch.setenv("SDTPU_PERF", "1")
        monkeypatch.delenv("SDTPU_PERF_PEAK_FLOPS", raising=False)
        led = perf.PerfLedger(max_groups=8)
        _record_one(led)
        (g,) = led.summary()["groups"]
        assert g["mfu"] is None          # unknown hardware: null, not 0

    def test_disabled_is_a_noop(self, monkeypatch):
        monkeypatch.delenv("SDTPU_PERF", raising=False)
        led = perf.PerfLedger(max_groups=8)
        _record_one(led)
        led.record_compile("chunk", 1.0)
        led.record_slo(tenant="t", cls="c", slo_s=1.0, latency_s=0.1)
        s = led.summary()
        assert s["enabled"] is False
        assert s["groups"] == [] and s["slo"] == [] and s["compiles"] == {}
        assert led.last_dispatch() is None

    def test_group_ring_evicts_oldest_and_counts_it(self, monkeypatch):
        monkeypatch.setenv("SDTPU_PERF", "1")
        led = perf.PerfLedger(max_groups=2)
        for bucket in ("a", "b", "c"):
            _record_one(led, bucket=bucket)
        s = led.summary()
        assert [g["bucket"] for g in s["groups"]] == ["b", "c"]
        assert s["groups_evicted"] == 1  # dropped coverage is declared

    def test_slo_attainment_and_burn_rate(self, monkeypatch):
        # 1 miss in a 10-deep window against a 5% error budget: burn 2.0
        monkeypatch.setenv("SDTPU_PERF", "1")
        led = perf.PerfLedger(slo_target=0.95)
        for _ in range(9):
            led.record_slo(tenant="acme", cls="interactive",
                           slo_s=1.0, latency_s=0.2)
        led.record_slo(tenant="acme", cls="interactive",
                       slo_s=1.0, latency_s=3.0)   # late: burns budget
        (row,) = led.summary()["slo"]
        assert (row["tenant"], row["class"]) == ("acme", "interactive")
        assert row["total"] == 10 and row["met"] == 9
        assert row["attainment"] == pytest.approx(0.9)
        assert row["burn_rate"] == pytest.approx((1 / 10) / 0.05)

    def test_errored_request_burns_budget_even_if_fast(self, monkeypatch):
        monkeypatch.setenv("SDTPU_PERF", "1")
        led = perf.PerfLedger(slo_target=0.95)
        led.record_slo(tenant="t", cls="c", slo_s=1.0, latency_s=0.1,
                       ok=False)
        (row,) = led.summary()["slo"]
        assert row["met"] == 0

    def test_garbage_input_never_raises(self, monkeypatch):
        # telemetry must not fail the dispatch path
        monkeypatch.setenv("SDTPU_PERF", "1")
        led = perf.PerfLedger(max_groups=8)
        _record_one(led, cadence="not-a-number")
        assert led.summary()["groups"] == []


class TestPeakFlops:
    @pytest.fixture(autouse=True)
    def _no_override(self, monkeypatch):
        monkeypatch.delenv("SDTPU_PERF_PEAK_FLOPS", raising=False)

    def test_known_chips(self):
        assert perf.peak_flops_for("TPU v5p") == pytest.approx(459e12)
        assert perf.peak_flops_for("TPU v5e") == pytest.approx(197e12)
        assert perf.peak_flops_for("TPU v4") == pytest.approx(275e12)

    def test_int8_doubles_the_mxu_peak(self):
        assert perf.peak_flops_for("TPU v5p", "int8") \
            == pytest.approx(2 * 459e12)

    def test_unknown_hardware_is_none(self):
        assert perf.peak_flops_for("cpu") is None
        assert perf.peak_flops_for("") is None

    def test_env_override_wins_outright(self, monkeypatch):
        monkeypatch.setenv("SDTPU_PERF_PEAK_FLOPS", "123e9")
        assert perf.peak_flops_for("cpu") == pytest.approx(123e9)
        assert perf.peak_flops_for("TPU v4") == pytest.approx(123e9)


# -- executable census -------------------------------------------------------

class TestCensus:
    def test_mixed_traffic_holds_the_budget(self, engine, clean_ledger,
                                            monkeypatch):
        # real traffic on ONE bucket across the two budgeted axes: plain
        # bf16, step-cache (deepcache cadence 2), and the int8 rung —
        # cadence is a runtime arg, so this mints exactly 3 chunk
        # executables (2 step-cache variants x 2 precisions actually used)
        monkeypatch.setenv("SDTPU_PERF", "1")
        disp = _dispatcher(engine)
        disp.submit(payload(seed=41))
        disp.submit(payload(seed=42, override_settings={"deepcache": 2}))
        disp.submit(payload(seed=43,
                            override_settings={"precision": "int8"}))

        census = perf.executables_census(engine)
        assert census["alarm"] is False and census["over_budget"] == []
        assert census["budget"] == {"step_cache": 2, "precision": 3,
                                    "lora": 4, "per_bucket": 6}
        (row,) = [r for r in census["buckets"]
                  if r["bucket"] == "Euler a/4st 48x48 b2"]
        assert row["executables"] == 3
        assert row["step_cache_variants"] == 2
        assert row["precisions"] == ["bf16", "int8"]
        assert row["over_budget"] is False

        # the same traffic fed the ledger: three (bucket, cadence,
        # precision) groups, padding accounted (32x32 true vs 48x48 run)
        groups = {(g["cadence"], g["precision"]): g
                  for g in perf.LEDGER.summary()["groups"]
                  if g["bucket"] == "48x48"}
        assert set(groups) == {(1, "bf16"), (2, "bf16"), (1, "int8")}
        g = groups[(1, "bf16")]
        assert g["device_s"] > 0
        # 1 request padded to batch 2 at 48x48 vs one true 32x32 image
        assert g["padding_ratio"] == pytest.approx(
            (48 * 48 * 2) / (32 * 32), rel=1e-6)

    def test_synthetic_over_budget_trips_the_alarm(self):
        def key(sc, prec):
            return ("chunk", "Euler a", 4, 64, 64, 4, 1, False, 0, False,
                    "sd", sc, prec)

        keys = [key(False, "bf16"), key(True, "bf16"), key("half", "bf16")]
        census = perf.census_from_keys(keys)
        assert census["alarm"] is True
        assert census["over_budget"] == ["Euler a/4st 64x64 b4"]
        (row,) = census["buckets"]
        assert row["step_cache_variants"] == 3      # > the budget of 2
        assert row["over_budget"] is True

    def test_non_chunk_keys_are_counted_not_budgeted(self):
        census = perf.census_from_keys([("decode", 64, 64, 4)])
        assert census["buckets"] == []
        assert census["other_executables"] == 1
        assert census["alarm"] is False


# -- off-by-default byte identity -------------------------------------------

class TestByteIdentity:
    def test_perf_on_output_matches_perf_off(self, engine, clean_ledger,
                                             monkeypatch):
        disp = _dispatcher(engine)
        monkeypatch.delenv("SDTPU_PERF", raising=False)
        off = disp.submit(payload(seed=77))
        assert perf.LEDGER.last_dispatch() is None   # truly dormant

        monkeypatch.setenv("SDTPU_PERF", "1")
        on = disp.submit(payload(seed=77))
        assert on.images == off.images               # byte-identical pngs
        assert on.seeds == off.seeds
        last = perf.LEDGER.last_dispatch()
        assert last is not None and last["bucket"] == "48x48"
        assert last["precision"] == "bf16" and last["device_s"] > 0


# -- prometheus label hygiene ------------------------------------------------

class TestPromLabels:
    def test_sanitize_drops_controls_keeps_newline(self):
        assert obs_prom.sanitize_label_value("a\rb\x00c\x7fd") == "abcd"
        assert obs_prom.sanitize_label_value("a\nb") == "a\nb"
        assert len(obs_prom.sanitize_label_value("x" * 4096)) == 100

    def test_adversarial_tenant_renders_on_one_line(self, clean_ledger,
                                                    monkeypatch):
        monkeypatch.setenv("SDTPU_PERF", "1")
        perf.LEDGER.record_slo(tenant='evil"tenant\n\rX',
                               cls="interactive\x00", slo_s=1.0,
                               latency_s=0.5)
        body = obs_prom.render()
        lines = [ln for ln in body.splitlines()
                 if ln.startswith("sdtpu_fleet_slo_attainment{")]
        assert lines, "slo family missing from exposition"
        (line,) = lines
        # \r and NUL dropped by sanitation; " and \n escaped losslessly
        assert 'tenant="evil\\"tenant\\nX"' in line
        assert 'class="interactive"' in line
        assert line.endswith(" 1")
        assert "sdtpu_fleet_slo_burn_rate" in body

    def test_registry_rejects_bad_names_and_collisions(self):
        with pytest.raises(obs_prom.MetricRegistrationError):
            obs_prom.register_metric("Bad Name", "counter", "x")
        obs_prom.register_metric("sdtpu_test_collision_total",
                                 "counter", "x")
        with pytest.raises(obs_prom.MetricRegistrationError):
            obs_prom.register_metric("sdtpu_test_collision_total",
                                     "gauge", "x")


# -- flight recorder ---------------------------------------------------------

class TestFlightRec:
    def test_ring_stays_bounded_under_churn(self):
        rec = FlightRecorder(capacity=8)
        for i in range(100):
            rec.record(f"r{i}", "error", "boom", events=[])
        doc = rec.dump()
        assert doc["capacity"] == 8 and doc["count"] == 8
        assert len(rec) == 8
        assert [e["request_id"] for e in doc["entries"]] \
            == [f"r{i}" for i in range(92, 100)]

    def test_entries_carry_last_dispatch_perf(self, clean_ledger,
                                              monkeypatch):
        monkeypatch.setenv("SDTPU_PERF", "1")
        _record_one(perf.LEDGER)
        rec = FlightRecorder(capacity=2)
        entry = rec.record("rid-1", "interrupted", "detail", events=[])
        assert entry["perf"]["bucket"] == "64x64"
        assert entry["perf"]["precision"] == "bf16"

    def test_perf_field_is_null_before_any_dispatch(self, clean_ledger):
        rec = FlightRecorder(capacity=2)
        assert rec.record("rid-2", "error", "d", events=[])["perf"] is None


# -- HTTP surfaces -----------------------------------------------------------

def call(server, route, body=None, method=None):
    url = f"http://127.0.0.1:{server.port}{route}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method or ("POST" if data else "GET"),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read() or b"{}")


@pytest.fixture(scope="class")
def server(engine):
    from stable_diffusion_webui_distributed_tpu.server.api import ApiServer

    srv = ApiServer(engine, state=engine.state,
                    host="127.0.0.1", port=0).start()
    # the auto-created dispatcher carries the default 512x ladder; swap in
    # the test bucketer so any traffic shares this module's compile keys
    srv.dispatcher = _dispatcher(engine)
    yield srv
    srv.stop()


class TestEndpoints:
    def test_status_schema_snapshot(self, server):
        # the /internal/status contract: exact top-level shape, pinned so
        # panel consumers (and this repo's own tools) notice breakage
        out = call(server, "/internal/status")
        assert set(out) == {"model", "workers", "settings", "serving",
                            "pool", "obs", "progress", "timings", "logs"}
        # no pool installed: the block is a bare gate echo
        assert out["pool"] == {"enabled": False}
        assert set(out["progress"]) == {"job", "sampling_step",
                                        "sampling_steps", "fraction",
                                        "interrupted"}
        serving = out["serving"]
        assert serving is not None  # engine-backed: dispatcher is live
        for key in ("coalesce_window_s", "bucket_ladder", "batch_ladder",
                    "eta_overhead", "fleet", "requests", "dispatches"):
            assert key in serving, key

    def test_perf_endpoint_serves_ledger(self, server, clean_ledger,
                                         monkeypatch):
        monkeypatch.setenv("SDTPU_PERF", "1")
        _record_one(perf.LEDGER, bucket="48x48")
        perf.LEDGER.record_compile("chunk", 0.25)
        out = call(server, "/internal/perf")
        assert out["enabled"] is True
        assert [g["bucket"] for g in out["groups"]] == ["48x48"]
        assert out["compiles"]["chunk"]["count"] == 1
        assert out["slo_target"] == pytest.approx(0.95)

    def test_executables_endpoint_census(self, server):
        out = call(server, "/internal/executables")
        assert out["available"] is True
        assert out["alarm"] is False
        assert out["budget"]["per_bucket"] == 6
        assert isinstance(out["buckets"], list)

    def test_dispatcher_tier_journal_journey(self, server, monkeypatch):
        # the serving-tier lifecycle events (PR 9): a real engine dispatch
        # must journal the full received -> ... -> completed journey with
        # an intact causal parent chain
        from stable_diffusion_webui_distributed_tpu.obs import (
            journal as obs_journal,
        )

        monkeypatch.setenv("SDTPU_JOURNAL", "1")
        obs_journal.JOURNAL.clear()
        try:
            out = call(server, "/sdapi/v1/txt2img",
                       {"prompt": "a cow", "batch_size": 2, "seed": 3,
                        "steps": 4, "width": 32, "height": 32,
                        "request_id": "rid-disp-journey"})
            assert len(out["images"]) == 2
            events = call(server,
                          "/internal/journal?request_id=rid-disp-journey"
                          )["events"]
            names = [e["event"] for e in events]
            assert names == ["received", "bucketed", "coalesced_leader",
                             "dispatched", "decoded", "merged",
                             "completed"]
            by_name = {e["event"]: e for e in events}
            assert by_name["bucketed"]["attrs"]["bucket"] == "48x48"
            assert by_name["received"]["attrs"]["fingerprint"]
            assert by_name["completed"]["attrs"]["seeds"] == [3, 4]
            seqs = {e["seq"] for e in events}
            assert events[0]["parent"] is None
            assert all(e["parent"] in seqs for e in events[1:])
        finally:
            obs_journal.JOURNAL.clear()

    def test_autoscale_endpoint_audit_ring(self, server):
        slices.set_autoscale(None)
        try:
            assert call(server, "/internal/autoscale") == {"active": False}
            reg = slices.SliceRegistry()
            reg.register(slices.SliceInfo(name="s0", group="tiny/bf16",
                                          replicas=1, max_replicas=4))
            eng = slices.AutoscaleEngine(
                reg, quantile_source=lambda: 10.0, up_p95_s=5.0,
                down_p95_s=0.5, cooldown_s=0.0)   # registers itself
            assert eng.decide(), "expected an up decision"
            out = call(server, "/internal/autoscale")
            assert out["active"] is True
            assert out["decisions_total"] == 1
            (d,) = out["decisions"]
            assert d["direction"] == "up" and d["slice_name"] == "s0"
            assert d["decided_at"] > 0      # wall clock for correlation
            # no executor attached: the outcome field says so explicitly
            assert d["execution"] == {"outcome": "no_executor"}
        finally:
            slices.set_autoscale(None)

    def test_autoscale_audit_ring_is_bounded(self, monkeypatch):
        monkeypatch.setenv("SDTPU_AUTOSCALE_AUDIT", "4")
        try:
            reg = slices.SliceRegistry()
            reg.register(slices.SliceInfo(name="s0", group="g",
                                          min_replicas=1, max_replicas=2))
            p95 = [0.0]
            eng = slices.AutoscaleEngine(
                reg, quantile_source=lambda: p95[0], up_p95_s=5.0,
                down_p95_s=0.5, cooldown_s=0.0)
            for i in range(10):
                p95[0] = 10.0 if i % 2 == 0 else 0.1  # up, down, up, ...
                assert eng.decide()
            audit = eng.audit()
            assert audit["capacity"] == 4
            assert audit["decisions_total"] == 10
            assert len(audit["decisions"]) == 4     # ring wrapped
        finally:
            slices.set_autoscale(None)

    def test_profile_get_validates_seconds(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            call(server, "/internal/profile?seconds=abc")
        assert e.value.code == 422

    def test_profile_get_one_shot_capture(self, server, monkeypatch,
                                          tmp_path):
        # a real (tiny) jax.profiler capture; chdir jails the trace dir
        # under tmp so nothing lands in the repo
        monkeypatch.chdir(tmp_path)
        out = call(server, "/internal/profile?seconds=0.1&dir=t1")
        assert out["seconds"] == pytest.approx(0.1)
        assert out["captured_dir"] == os.path.join("profile-traces", "t1")
        assert (tmp_path / "profile-traces" / "t1").is_dir()

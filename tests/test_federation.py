"""Federation prober, TSDB durability, and notify delivery tests.

Covers the three observability pieces of the federation PR:

- obs/federation.py — prober staleness math (RTT-midpoint anchoring),
  per-node fault isolation, fleet aggregates, the /internal/fleet
  endpoint, and the hung-worker timeout regression;
- obs/tsdb.py durability — dump/load round-trips, corrupt-snapshot
  tolerance, cross-boot future-timestamp drops, and the
  restart-equivalence contract (a quantile window spanning a restart
  equals an uninterrupted run);
- obs/notify.py — webhook delivery, retry with backoff, dedup, and
  the gate-off no-op;

plus the hash-pinned gate-off golden proving the serving path is
byte-identical with every new knob unset.
"""

import json
import socket
import threading
import time
import types
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from stable_diffusion_webui_distributed_tpu.obs import alerts as obs_alerts
from stable_diffusion_webui_distributed_tpu.obs import (
    federation as obs_fed,
)
from stable_diffusion_webui_distributed_tpu.obs import journal as obs_journal
from stable_diffusion_webui_distributed_tpu.obs import notify as obs_notify
from stable_diffusion_webui_distributed_tpu.obs import stitch as obs_stitch
from stable_diffusion_webui_distributed_tpu.obs import tsdb as obs_tsdb
from stable_diffusion_webui_distributed_tpu.models.configs import TINY
from stable_diffusion_webui_distributed_tpu.pipeline.engine import Engine
from stable_diffusion_webui_distributed_tpu.pipeline.payload import (
    GenerationPayload,
)
from stable_diffusion_webui_distributed_tpu.runtime.interrupt import (
    GenerationState,
)
from stable_diffusion_webui_distributed_tpu.serving.bucketer import (
    ShapeBucketer,
)
from stable_diffusion_webui_distributed_tpu.serving.dispatcher import (
    ServingDispatcher,
)

from test_goldens import _check
from test_pipeline import init_params


@pytest.fixture()
def fed_on(monkeypatch):
    monkeypatch.setenv("SDTPU_FEDERATION", "1")
    yield
    obs_fed.reset()


class FakeClock:
    """Settable monotonic clock for deterministic staleness math."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def scripted_clock(values, last):
    """Clock returning ``values`` in order, then ``last`` forever."""
    it = iter(values)

    def clock():
        try:
            return next(it)
        except StopIteration:
            return last

    return clock


class FakeBackend:
    """In-process fed_fetch seam: returns canned documents or raises."""

    def __init__(self, metrics_text="", tsdb_doc=None, exc=None):
        self.metrics_text = metrics_text
        self.tsdb_doc = tsdb_doc if tsdb_doc is not None else {"series": {}}
        self.exc = exc

    def fed_fetch(self):
        if self.exc is not None:
            raise self.exc
        return self.metrics_text, self.tsdb_doc


class FakeWorker:
    def __init__(self, label, backend):
        self.label = label
        self.backend = backend


_METRICS_A = "\n".join([
    "# HELP sdtpu_worker_requests_total total requests",
    "# TYPE sdtpu_worker_requests_total counter",
    'sdtpu_worker_requests_total{worker="a"} 3',
    'sdtpu_worker_requests_total{worker="x"} 1',
    'sdtpu_worker_failures_total{worker="a"} 1',
    "not a metric line at all",
])

_TSDB_A = {"series": {
    "queue_wait_p95_s": {"count": 1, "latest": [5.0, 0.5],
                         "samples": [[5.0, 0.5]]},
    "e2e_p95_s": {"count": 1, "latest": [5.0, 1.25],
                  "samples": [[5.0, 1.25]]},
}}


# -- prometheus text digest ---------------------------------------------------

class TestParsePromText:
    def test_sums_families_across_label_sets(self):
        out = obs_fed.parse_prom_text(_METRICS_A)
        assert out["sdtpu_worker_requests_total"] == 4.0
        assert out["sdtpu_worker_failures_total"] == 1.0

    def test_tolerates_comments_blanks_and_garbage(self):
        text = "# HELP x\n\nbroken\nalso broken nan-ish value?\nf 2\nf 3\n"
        assert obs_fed.parse_prom_text(text) == {"f": 5.0}
        assert obs_fed.parse_prom_text("") == {}
        assert obs_fed.parse_prom_text(None) == {}


# -- staleness deadline -------------------------------------------------------

class TestStaleAfter:
    def test_scales_with_the_tsdb_interval(self, monkeypatch):
        monkeypatch.setenv("SDTPU_TSDB_INTERVAL_S", "2.0")
        assert obs_fed.stale_after_s() == pytest.approx(6.0)

    def test_floored_for_fast_test_cadences(self, monkeypatch):
        monkeypatch.setenv("SDTPU_TSDB_INTERVAL_S", "0.01")
        assert obs_fed.stale_after_s() == pytest.approx(
            obs_fed.STALE_FLOOR_S)


# -- the prober ---------------------------------------------------------------

class TestProberTick:
    def test_gate_off_tick_is_a_noop(self, monkeypatch):
        monkeypatch.delenv("SDTPU_FEDERATION", raising=False)
        store = obs_tsdb.SeriesStore(points=64)
        prober = obs_fed.FederationProber(
            source=[FakeWorker("a", FakeBackend(_METRICS_A))],
            store=store, clock=FakeClock(10.0))
        assert prober.tick() == 0
        assert store.names() == []

    def test_tick_records_worker_and_fleet_series(self, fed_on,
                                                  monkeypatch):
        from stable_diffusion_webui_distributed_tpu.obs import (
            prometheus as obs_prom,
        )

        monkeypatch.setattr(obs_prom, "fleet_queue_wait_p95", lambda: 0.0)
        store = obs_tsdb.SeriesStore(points=64)
        workers = [
            FakeWorker("a", FakeBackend(_METRICS_A, _TSDB_A)),
            FakeWorker("b", FakeBackend(
                'sdtpu_worker_requests_total{worker="b"} 10\n')),
        ]
        prober = obs_fed.FederationProber(source=workers, store=store,
                                          clock=FakeClock(10.0))
        landed = prober.tick(now=10.0)
        assert landed > 0
        assert store.latest("worker:a/requests_total")[1] == 4.0
        assert store.latest("worker:a/failures_total")[1] == 1.0
        assert store.latest("worker:a/error_rate")[1] == pytest.approx(0.25)
        assert store.latest("worker:a/queue_wait_p95_s")[1] == 0.5
        assert store.latest("worker:a/e2e_p95_s")[1] == 1.25
        assert store.latest("worker:b/error_rate")[1] == 0.0
        # no remote tsdb doc series for b: the p95 defaults, never absent
        assert store.latest("worker:b/queue_wait_p95_s")[1] == 0.0
        assert store.latest("fleet/error_rate")[1] == pytest.approx(0.125)
        assert store.latest("fleet/queue_wait_p95_s")[1] == 0.5
        assert store.latest("fleet/worker_stale_count")[1] == 0.0
        assert store.latest("fleet/poll_failures_total")[1] == 0.0

    def test_staleness_anchors_to_the_rtt_midpoint(self, fed_on):
        # fetch bracketed at t0=100, t1=102: the document is attributed
        # to 101 (stitch's clock-correction pattern), so at now=102 the
        # worker is 1.0s stale — data age, not transfer time
        store = obs_tsdb.SeriesStore(points=64)
        prober = obs_fed.FederationProber(
            source=[FakeWorker("a", FakeBackend(_METRICS_A))],
            store=store, clock=scripted_clock([100.0, 102.0], 102.0))
        prober.tick(now=102.0)
        assert store.latest("worker:a/staleness_s")[1] == pytest.approx(1.0)
        assert store.latest("worker:a/poll_rtt_s")[1] == pytest.approx(2.0)

    def test_per_node_fault_isolation(self, fed_on, monkeypatch):
        from stable_diffusion_webui_distributed_tpu.obs import (
            prometheus as obs_prom,
        )

        monkeypatch.setattr(obs_prom, "fleet_queue_wait_p95", lambda: 0.0)
        monkeypatch.setenv("SDTPU_JOURNAL", "1")
        store = obs_tsdb.SeriesStore(points=64)
        workers = [
            FakeWorker("good", FakeBackend(_METRICS_A, _TSDB_A)),
            FakeWorker("fedbad", FakeBackend(
                exc=ConnectionError("worker down"))),
        ]
        prober = obs_fed.FederationProber(source=workers, store=store,
                                          clock=FakeClock(10.0))
        prober.tick(now=10.0)
        # the healthy worker's sweep is untouched by the dead one
        assert store.latest("worker:good/error_rate")[1] == \
            pytest.approx(0.25)
        # the dead worker contributes staleness + a 1.0 error share only
        assert store.latest("worker:fedbad/staleness_s") is not None
        assert store.latest("worker:fedbad/error_rate") is None
        assert store.latest("fleet/error_rate")[1] == pytest.approx(0.625)
        assert store.latest("fleet/poll_failures_total")[1] == 1.0
        doc = prober.summary()
        assert doc["workers"]["fedbad"]["failures"] == 1
        assert "ConnectionError" in doc["workers"]["fedbad"]["last_error"]
        assert doc["workers"]["good"]["last_error"] is None
        events = obs_journal.JOURNAL.events_for("federation-fedbad")
        assert any(e["event"] == "federation_poll_failed" for e in events)

    def test_dead_worker_goes_stale_and_counts(self, fed_on):
        clock = FakeClock(0.0)
        backend = FakeBackend(_METRICS_A)
        store = obs_tsdb.SeriesStore(points=64)
        prober = obs_fed.FederationProber(
            source=[FakeWorker("w", backend)], store=store, clock=clock)
        prober.tick(now=0.0)
        assert store.latest("fleet/worker_stale_count")[1] == 0.0
        # the worker dies; the next sweep is far past the deadline
        backend.exc = ConnectionError("gone")
        clock.t = 100.0
        prober.tick(now=100.0)
        assert store.latest("worker:w/staleness_s")[1] == \
            pytest.approx(100.0)
        assert store.latest("fleet/worker_stale_count")[1] == 1.0
        doc = prober.summary()
        assert doc["workers"]["w"]["stale"] is True

    def test_hung_worker_cannot_stall_the_tick(self, fed_on, monkeypatch):
        # regression: a worker that accepts the TCP connection but never
        # responds must cost one obs-plane timeout, not a hung sweep
        monkeypatch.setenv("SDTPU_OBS_HTTP_TIMEOUT_S", "0.2")
        srv = socket.socket()
        try:
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            port = srv.getsockname()[1]
            backend = types.SimpleNamespace(
                address="127.0.0.1", port=port, tls=False)
            prober = obs_fed.FederationProber(
                source=[FakeWorker("hung", backend)],
                store=obs_tsdb.SeriesStore(points=64))
            t0 = time.monotonic()
            prober.tick()
            elapsed = time.monotonic() - t0
            assert elapsed < 2.0
            doc = prober.summary()
            assert doc["workers"]["hung"]["failures"] == 1
            assert doc["workers"]["hung"]["last_error"] is not None
        finally:
            srv.close()


# -- module plumbing: scale signal, alert rules, endpoint ---------------------

class TestModuleSurfaces:
    def test_fleet_scale_signal_is_gated(self, monkeypatch):
        monkeypatch.delenv("SDTPU_FEDERATION", raising=False)
        assert obs_fed.fleet_queue_wait_p95() == 0.0

    def test_fleet_scale_signal_reads_the_latest_aggregate(
            self, fed_on, monkeypatch):
        obs_tsdb.STORE.record("fleet/queue_wait_p95_s", 7.5)
        try:
            assert obs_fed.fleet_queue_wait_p95() == 7.5
        finally:
            obs_tsdb.reset()

    def test_autoscaler_source_lifts_to_the_fleet_signal(
            self, fed_on, monkeypatch):
        from stable_diffusion_webui_distributed_tpu.fleet import slices

        obs_tsdb.STORE.record("fleet/queue_wait_p95_s", 9.0)
        try:
            assert slices._default_quantile_source() >= 9.0
        finally:
            obs_tsdb.reset()

    def test_fleet_alert_rules_are_registered(self):
        rules = obs_alerts.registered_rules()
        assert "worker_metrics_stale" in rules
        assert "fleet_error_rate" in rules

    def test_fleet_endpoint_schema(self):
        from stable_diffusion_webui_distributed_tpu.runtime.config import (
            ConfigModel,
        )
        from stable_diffusion_webui_distributed_tpu.scheduler.worker \
            import StubBackend, WorkerNode
        from stable_diffusion_webui_distributed_tpu.scheduler.world \
            import World
        from stable_diffusion_webui_distributed_tpu.server.api import (
            ApiServer,
        )

        w = World(ConfigModel())
        w.add_worker(WorkerNode("m", StubBackend(), master=True,
                                avg_ipm=10.0))
        srv = ApiServer(w, state=GenerationState(),
                        host="127.0.0.1", port=0).start()
        try:
            url = f"http://127.0.0.1:{srv.port}/internal/fleet"
            with urllib.request.urlopen(url, timeout=30) as r:
                doc = json.loads(r.read())
        finally:
            srv.stop()
        assert set(doc) == {"enabled", "stale_after_s", "ticks",
                            "polls_total", "poll_failures_total",
                            "daemon", "workers", "fleet"}
        assert doc["enabled"] is False
        assert set(doc["fleet"]) == {"queue_wait_p95_s", "error_rate",
                                     "worker_stale_count"}


# -- obs-plane HTTP timeout knob ----------------------------------------------

class TestHttpTimeoutKnob:
    def test_defaults_follow_the_caller(self, monkeypatch):
        monkeypatch.delenv("SDTPU_OBS_HTTP_TIMEOUT_S", raising=False)
        assert obs_stitch.http_timeout_s() == obs_stitch.FETCH_TIMEOUT_S
        assert obs_stitch.http_timeout_s(3.0) == 3.0

    def test_env_override_and_floor(self, monkeypatch):
        monkeypatch.setenv("SDTPU_OBS_HTTP_TIMEOUT_S", "0.5")
        assert obs_stitch.http_timeout_s() == 0.5
        monkeypatch.setenv("SDTPU_OBS_HTTP_TIMEOUT_S", "0.001")
        assert obs_stitch.http_timeout_s() == 0.05

    def test_http_backend_resolves_the_knob(self, monkeypatch):
        from stable_diffusion_webui_distributed_tpu.scheduler.worker \
            import HTTPBackend

        monkeypatch.setenv("SDTPU_OBS_HTTP_TIMEOUT_S", "0.7")
        b = HTTPBackend("127.0.0.1", 1)
        try:
            assert b.timeout == 0.7
        finally:
            b.close()
        monkeypatch.delenv("SDTPU_OBS_HTTP_TIMEOUT_S", raising=False)
        b = HTTPBackend("127.0.0.1", 1)
        try:
            assert b.timeout == 3.0
        finally:
            b.close()


# -- notify delivery ----------------------------------------------------------

@pytest.fixture()
def hook(monkeypatch):
    """Local webhook capture server; scripted per-request statuses."""
    received, statuses = [], deque()

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
            status = statuses.popleft() if statuses else 200
            # record before responding: the client may assert the moment
            # it sees the 2xx, so the append must happen-before it
            if 200 <= status < 300:
                received.append(body)
            self.send_response(status)
            self.end_headers()
            self.wfile.write(b"ok")

        def log_message(self, *args):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    monkeypatch.setenv(
        "SDTPU_NOTIFY_URL",
        f"http://127.0.0.1:{srv.server_address[1]}/hook")
    monkeypatch.setenv("SDTPU_NOTIFY_DEDUP_S", "60")
    yield {"received": received, "statuses": statuses}
    srv.shutdown()
    srv.server_close()


class TestNotify:
    def test_gate_off_is_a_noop(self, monkeypatch):
        monkeypatch.delenv("SDTPU_NOTIFY_URL", raising=False)
        n = obs_notify.Notifier()
        assert n.notify_transition("r", "firing", 1.0, "d") is False
        assert n.counts() == {}
        assert n.summary()["enabled"] is False

    def test_delivers_one_document_per_transition(self, hook):
        n = obs_notify.Notifier()
        try:
            assert n.notify_transition("burn", "firing", 2.5, "hot") is True
            assert n.flush(5.0) is True
            assert n.counts() == {"sent": 1}
            (body,) = hook["received"]
            assert body["rule"] == "burn"
            assert body["event"] == "firing"
            assert body["value"] == 2.5
            assert body["detail"] == "hot"
            assert "ts" in body
        finally:
            n.stop()

    def test_dedup_window_drops_repeats_not_transitions(self, hook):
        n = obs_notify.Notifier()
        try:
            assert n.notify_transition("r", "firing", 1.0, "d") is True
            assert n.notify_transition("r", "firing", 1.0, "d") is False
            # a different transition of the same rule is not a repeat
            assert n.notify_transition("r", "resolved", 0.0, "d") is True
            assert n.flush(5.0) is True
            assert n.counts() == {"sent": 2, "deduped": 1}
            assert len(hook["received"]) == 2
        finally:
            n.stop()

    def test_retries_through_a_transient_500(self, hook):
        hook["statuses"].append(500)
        n = obs_notify.Notifier()
        try:
            assert n.notify_transition("r", "firing", 1.0, "d") is True
            assert n.flush(5.0) is True
            assert n.counts() == {"sent": 1}
            assert len(hook["received"]) == 1
        finally:
            n.stop()

    def test_exhausted_retries_count_as_failed(self, hook):
        hook["statuses"].extend([500] * obs_notify._MAX_ATTEMPTS)
        n = obs_notify.Notifier()
        try:
            assert n.notify_transition("r", "firing", 1.0, "d") is True
            assert n.flush(5.0) is True
            assert n.counts() == {"failed": 1}
            assert hook["received"] == []
        finally:
            n.stop()


# -- TSDB durability ----------------------------------------------------------

class TestDurability:
    def _filled(self, n=10, base=None):
        now = time.monotonic() if base is None else base
        store = obs_tsdb.SeriesStore(points=64)
        for i in range(n):
            store.record("queue_wait_p95_s", float(i % 7),
                         t=now - 60.0 + i)
        return store, now

    def test_dump_load_round_trip(self):
        a, _now = self._filled()
        doc = a.dump()
        assert doc["schema"] == 1
        b = obs_tsdb.SeriesStore(points=64)
        assert b.load_merge(doc) == 10
        assert b.window("queue_wait_p95_s", 0) == \
            a.window("queue_wait_p95_s", 0)
        # restored samples do not count as "sampled this process"
        assert b.stats()["samples_total"] == 0

    def test_load_merge_tolerates_garbage(self):
        b = obs_tsdb.SeriesStore(points=64)
        assert b.load_merge(None) == 0
        assert b.load_merge([1, 2]) == 0
        assert b.load_merge({"series": "nope"}) == 0
        assert b.load_merge({"series": {"s": [[1.0], ["x", "y"],
                                              "junk"]}}) == 0
        assert b.names() == []

    def test_future_timestamps_from_a_prior_boot_are_dropped(self):
        b = obs_tsdb.SeriesStore(points=64)
        future = time.monotonic() + 1e6
        assert b.load_merge({"series": {"s": [[future, 1.0]]}}) == 0
        assert b.names() == []

    def test_corrupt_snapshot_file_loads_as_nothing(self, tmp_path):
        path = tmp_path / "tsdb_snapshot.json"
        path.write_text('{"schema": 1, "series": {"s": [[1.0, 2.0')
        b = obs_tsdb.SeriesStore(points=64)
        assert obs_tsdb.load_snapshot(store=b, path=str(path)) == 0
        assert obs_tsdb.load_snapshot(
            store=b, path=str(tmp_path / "missing.json")) == 0
        assert b.names() == []

    def test_save_snapshot_is_gated_on_the_dir_knob(self, monkeypatch):
        monkeypatch.delenv("SDTPU_TSDB_DIR", raising=False)
        a, _now = self._filled()
        assert obs_tsdb.save_snapshot(store=a) is False

    def test_save_load_via_the_dir_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SDTPU_TSDB_DIR", str(tmp_path))
        a, _now = self._filled()
        assert obs_tsdb.save_snapshot(store=a) is True
        assert (tmp_path / obs_tsdb.SNAPSHOT_BASENAME).exists()
        b = obs_tsdb.SeriesStore(points=64)
        assert obs_tsdb.load_snapshot(store=b) == 10
        assert b.window("queue_wait_p95_s", 0) == \
            a.window("queue_wait_p95_s", 0)

    def test_quantile_window_spans_the_restart(self, tmp_path):
        # the acceptance contract: save at sample 10, "restart" into a
        # fresh store, record the rest — a quantile_over_time window
        # spanning the restart equals the uninterrupted run's
        now = time.monotonic()
        ts = [now - 60.0 + i for i in range(20)]
        vals = [float((i * 13) % 29) for i in range(20)]
        uninterrupted = obs_tsdb.SeriesStore(points=64)
        for t, v in zip(ts, vals):
            uninterrupted.record("queue_wait_p95_s", v, t=t)
        a = obs_tsdb.SeriesStore(points=64)
        for t, v in zip(ts[:10], vals[:10]):
            a.record("queue_wait_p95_s", v, t=t)
        path = str(tmp_path / "snap.json")
        assert obs_tsdb.save_snapshot(store=a, path=path) is True
        b = obs_tsdb.SeriesStore(points=64)
        assert obs_tsdb.load_snapshot(store=b, path=path) == 10
        for t, v in zip(ts[10:], vals[10:]):
            b.record("queue_wait_p95_s", v, t=t)
        for q in (0.5, 0.95, 0.99):
            assert b.quantile_over_time(
                "queue_wait_p95_s", q, 120.0, now=now) == \
                uninterrupted.quantile_over_time(
                    "queue_wait_p95_s", q, 120.0, now=now)

    def test_reset_is_the_restart(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SDTPU_TSDB", "1")
        monkeypatch.setenv("SDTPU_TSDB_DIR", str(tmp_path))
        obs_tsdb.reset()
        obs_tsdb.STORE.record("queue_wait_p95_s", 4.0)
        assert obs_tsdb.save_snapshot() is True
        obs_tsdb.reset()  # the restart: a rebuilt store merges the disk
        assert "queue_wait_p95_s" in obs_tsdb.STORE.names()
        assert obs_tsdb.STORE.latest("queue_wait_p95_s")[1] == 4.0
        monkeypatch.delenv("SDTPU_TSDB", raising=False)
        monkeypatch.delenv("SDTPU_TSDB_DIR", raising=False)
        obs_tsdb.reset()
        assert obs_tsdb.STORE.names() == []


# -- the gate-off serving path is byte-identical -----------------------------

class TestDefaultPathPinned:
    def test_federation_off_serving_path_hash_pinned(self, monkeypatch):
        for var in ("SDTPU_TSDB", "SDTPU_ALERTS", "SDTPU_FEDERATION",
                    "SDTPU_NOTIFY_URL", "SDTPU_TSDB_DIR"):
            monkeypatch.delenv(var, raising=False)
        obs_tsdb.reset()
        obs_alerts.reset()
        obs_fed.reset()
        obs_notify.reset()
        engine = Engine(TINY, init_params(TINY), chunk_size=4,
                        state=GenerationState())
        disp = ServingDispatcher(
            engine, bucketer=ShapeBucketer(shapes=[(32, 32)], batches=[1]),
            window=0.0)
        r = disp.submit(GenerationPayload(
            prompt="a golden scenario cow", width=32, height=32,
            steps=4, seed=4321, sampler_name="Euler a"))
        _check("serving/federation-off-default", r)
        # and nothing leaked into any of the new planes along the way
        assert obs_tsdb.STORE.names() == []
        assert obs_alerts.ENGINE.history() == []
        assert obs_fed.summary()["workers"] == {}
        assert obs_notify.summary()["outcomes"] == {}
